// tpuraft native KV storage engine.
//
// Reference parity: the role RocksDB (C++, via rocksdbjni) plays under
// rhea:storage/RocksRawKVStore — the durable ordered-KV engine shared by
// every RegionEngine of a process (SURVEY.md §3.2/§3.4).  Purpose-built
// for RheaKV's access pattern — point ops + range scans from a
// single-writer state-machine thread.  TWO storage modes:
//
// MEMTABLE mode (memtable_budget = 0, the original engine): ordered
// in-memory tables + CRC-framed WAL + atomic full checkpoint that
// truncates the WAL.  Datasets must fit RAM; checkpoints are O(dataset).
//
// LSM mode (memtable_budget > 0 via tkv_open2 — VERDICT r1 #7, the
// RocksDB >RAM role): when the memtable reaches the budget it SPILLS to
// an immutable sorted-run file (run_<seq>.sst: per-column sorted points
// with tombstone flags + range tombstones, CRC trailer, mmap'd with a
// sparse in-memory index) listed in an atomically-rewritten manifest;
// the WAL truncates at each spill, so recovery replays at most one
// memtable's worth.  Reads merge memtable -> runs newest-first with
// point/range tombstones masking older eras.  A background thread
// compacts when runs exceed max_runs: size-tiered pick-K — the cheapest
// contiguous window of runs merges into one (tombstones drop only on
// bottom-tier merges), so compaction I/O per cycle is independent of
// total store size — immutable runs swap under the store mutex, writers
// only ever touch the memtable.  Working sets page via mmap, so datasets
// several times RAM (or budget) stay serviceable.
//
// Columns (fixed): 0=data 1=sequence 2=lock 3=meta.  Column semantics
// (what a sequence/lock value means) live in the Python wrapper
// (tpuraft/rheakv/native_store.py) — apply-time logic is single-threaded
// through the raft state machine, so read-modify-write up there is safe.
//
// On-disk layout under the store dir:
//   wal.log     repeated [ u32le len | u32le crc32(payload) | payload ]
//               payload = 1+ ops: op(1) col(1) klen(4) key vlen(4) val
//               op: 1=put 2=delete 3=delete_range(key=start, val=end)
//               One record per write call -> each call is atomic; a torn
//               tail (short frame or CRC mismatch) is dropped on replay.
//   checkpoint  magic "TKV1" | per col: u32 count, (klen key vlen val)* |
//               u32 crc32(everything after magic)
//               written tmp+fsync+rename+dirsync, then the WAL truncates.
//
// Exposed as a C ABI for ctypes.  All returned buffers are malloc'd and
// released with tkv_free.

#include <atomic>
#include <cerrno>
#include <string_view>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <tuple>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

constexpr int kNumCols = 4;
constexpr char kCkptMagic[4] = {'T', 'K', 'V', '1'};
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDelete = 2;
constexpr uint8_t kOpDeleteRange = 3;
constexpr int64_t kDefaultCkptWalBytes = 64LL << 20;

uint32_t load_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
void put_u32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

uint32_t crc32_of(const void* data, size_t n) {
  return static_cast<uint32_t>(
      crc32(0L, static_cast<const Bytef*>(data), static_cast<uInt>(n)));
}

bool fsync_fd(int fd) { return fsync(fd) == 0; }

bool fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = fsync(fd) == 0;
  close(fd);
  return ok;
}

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

using Table = std::map<std::string, std::string>;

constexpr char kRunMagic[4] = {'T', 'K', 'R', '1'};
constexpr uint8_t kPtLive = 0;
constexpr uint8_t kPtTomb = 1;
constexpr size_t kIdxStride = 64;  // sparse index: every Nth point

// An immutable sorted-run file (LSM mode), mmap'd.
// Layout: magic | per col: [u32 n_points, points..., u32 n_ranges,
// ranges...] | u32 crc(body).  point = u8 flag u32 klen key u32 vlen
// val; range = u32 slen s u32 elen e (end empty = +inf).
struct Run {
  std::string path;
  uint32_t seq = 0;
  int fd = -1;
  uint8_t* map = reinterpret_cast<uint8_t*>(MAP_FAILED);
  size_t map_len = 0;

  struct ColIdx {
    uint32_t n_points = 0;
    size_t points_off = 0;   // file offset of first point entry
    size_t points_end = 0;
    // sparse index: (key of point i*kIdxStride, its file offset)
    std::vector<std::pair<std::string, size_t>> sparse;
    std::vector<std::pair<std::string, std::string>> ranges;
    // lazily-built full offsets (reverse scans); empty until needed
    std::vector<uint32_t> all_offsets;
  };
  ColIdx cols[kNumCols];

  ~Run() {
    if (map != MAP_FAILED) munmap(map, map_len);
    if (fd >= 0) close(fd);
  }
};

struct Store {
  std::mutex mu;
  std::string dir;
  Table cols[kNumCols];
  int wal_fd = -1;
  int64_t wal_bytes = 0;
  bool sync = true;
  int64_t ckpt_wal_bytes = kDefaultCkptWalBytes;
  int64_t ckpt_retry_floor = 0;  // backoff marker after a failed auto-ckpt

  // -- LSM mode (memtable_budget > 0) --------------------------------------
  int64_t memtable_budget = 0;        // 0 = memtable mode (legacy)
  int64_t max_runs = 6;
  int64_t mem_bytes = 0;              // approx bytes held by cols+dead+ranges
  Table dead[kNumCols];               // point tombstones (key -> "")
  std::vector<std::pair<std::string, std::string>> range_dead[kNumCols];
  std::vector<std::unique_ptr<Run>> runs;  // oldest .. newest
  uint32_t next_run_seq = 1;
  // background compaction (size-tiered pick-K; see compactor_main)
  std::thread compactor;
  std::condition_variable compact_cv;
  bool stopping = false;
  bool compact_running = false;
  int64_t compactions = 0;               // cycles completed
  int64_t compact_input_bytes = 0;       // cumulative input bytes merged
  int64_t compact_last_input_bytes = 0;  // last cycle's input bytes

  bool lsm() const { return memtable_budget > 0; }

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string ckpt_path() const { return dir + "/checkpoint"; }
  std::string manifest_path() const { return dir + "/manifest"; }
};

// -- op encoding shared by WAL records and tkv_apply_batch ------------------

// Validates and applies one op stream to the tables. Returns false on a
// malformed stream (nothing about partial application matters to callers:
// WAL replay treats malformed == torn tail, and tkv_apply_batch validates
// before applying).
bool parse_ops(const uint8_t* p, size_t n,
               std::vector<std::tuple<uint8_t, uint8_t, std::string,
                                      std::string>>* out) {
  size_t off = 0;
  while (off < n) {
    if (off + 2 + 4 > n) return false;
    uint8_t op = p[off], col = p[off + 1];
    off += 2;
    if (op < kOpPut || op > kOpDeleteRange || col >= kNumCols) return false;
    uint32_t klen = load_u32(p + off);
    off += 4;
    if (off + klen + 4 > n) return false;
    std::string key(reinterpret_cast<const char*>(p + off), klen);
    off += klen;
    uint32_t vlen = load_u32(p + off);
    off += 4;
    if (off + vlen > n) return false;
    std::string val(reinterpret_cast<const char*>(p + off), vlen);
    off += vlen;
    out->emplace_back(op, col, std::move(key), std::move(val));
  }
  return true;
}

void apply_ops(Store* s,
               const std::vector<std::tuple<uint8_t, uint8_t, std::string,
                                            std::string>>& ops) {
  for (const auto& [op, col, key, val] : ops) {
    Table& t = s->cols[col];
    if (!s->lsm()) {
      switch (op) {
        case kOpPut:
          t[key] = val;
          break;
        case kOpDelete:
          t.erase(key);
          break;
        case kOpDeleteRange: {
          auto lo = key.empty() ? t.begin() : t.lower_bound(key);
          auto hi = val.empty() ? t.end() : t.lower_bound(val);
          t.erase(lo, hi);
          break;
        }
      }
      continue;
    }
    // LSM mode: deletions become tombstones so older runs stay masked
    Table& dd = s->dead[col];
    switch (op) {
      case kOpPut: {
        auto [it, inserted] = t.insert_or_assign(key, val);
        (void)it;
        s->mem_bytes += static_cast<int64_t>(key.size() + val.size());
        auto di = dd.find(key);
        if (di != dd.end()) {
          s->mem_bytes -= static_cast<int64_t>(di->first.size());
          dd.erase(di);
        }
        break;
      }
      case kOpDelete: {
        auto li = t.find(key);
        if (li != t.end()) {
          s->mem_bytes -=
              static_cast<int64_t>(li->first.size() + li->second.size());
          t.erase(li);
        }
        if (dd.emplace(key, std::string()).second)
          s->mem_bytes += static_cast<int64_t>(key.size());
        break;
      }
      case kOpDeleteRange: {
        auto lo = key.empty() ? t.begin() : t.lower_bound(key);
        auto hi = val.empty() ? t.end() : t.lower_bound(val);
        for (auto it = lo; it != hi; ++it)
          s->mem_bytes -=
              static_cast<int64_t>(it->first.size() + it->second.size());
        t.erase(lo, hi);
        // point tombstones inside the range are subsumed by it
        auto dlo = key.empty() ? dd.begin() : dd.lower_bound(key);
        auto dhi = val.empty() ? dd.end() : dd.lower_bound(val);
        for (auto it = dlo; it != dhi; ++it)
          s->mem_bytes -= static_cast<int64_t>(it->first.size());
        dd.erase(dlo, dhi);
        s->range_dead[col].emplace_back(key, val);
        s->mem_bytes += static_cast<int64_t>(key.size() + val.size());
        break;
      }
    }
  }
}

// -- LSM runs (memtable_budget > 0) -----------------------------------------

bool write_all_fd(int fd, const void* buf, size_t len, std::string* err) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t w = write(fd, p, len);
    if (w < 0) {
      if (errno == EINTR) continue;
      *err = std::string("write: ") + strerror(errno);
      return false;
    }
    p += w;
    len -= static_cast<size_t>(w);
  }
  return true;
}

// Serialize (live, dead, ranges) into a run file: tmp + fsync + rename.
bool run_write(Store* s, const std::string& path, const Table live[],
               const Table dead[],
               const std::vector<std::pair<std::string, std::string>> ranges[],
               std::string* err) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    *err = std::string("run tmp open: ") + strerror(errno);
    return false;
  }
  uLong crc = crc32(0L, Z_NULL, 0);
  auto emit = [&](const void* p, size_t n) -> bool {
    crc = crc32(crc, static_cast<const Bytef*>(p), static_cast<uInt>(n));
    return write_all_fd(fd, p, n, err);
  };
  bool ok = write_all_fd(fd, kRunMagic, 4, err);  // magic not in crc
  for (int c = 0; ok && c < kNumCols; ++c) {
    // merged sorted points: live + dead (both std::map -> ordered merge)
    uint32_t n = static_cast<uint32_t>(live[c].size() + dead[c].size());
    ok = ok && emit(&n, 4);
    auto li = live[c].begin();
    auto di = dead[c].begin();
    while (ok && (li != live[c].end() || di != dead[c].end())) {
      bool take_live =
          di == dead[c].end() ||
          (li != live[c].end() && li->first < di->first);
      uint8_t flag = take_live ? kPtLive : kPtTomb;
      const std::string& k = take_live ? li->first : di->first;
      const std::string* v = take_live ? &li->second : nullptr;
      uint32_t klen = static_cast<uint32_t>(k.size());
      uint32_t vlen = v ? static_cast<uint32_t>(v->size()) : 0;
      ok = ok && emit(&flag, 1) && emit(&klen, 4) && emit(k.data(), klen) &&
           emit(&vlen, 4) && (vlen == 0 || emit(v->data(), vlen));
      if (take_live) ++li; else ++di;
    }
    uint32_t nr = static_cast<uint32_t>(ranges[c].size());
    ok = ok && emit(&nr, 4);
    for (size_t i = 0; ok && i < ranges[c].size(); ++i) {
      uint32_t sl = static_cast<uint32_t>(ranges[c][i].first.size());
      uint32_t el = static_cast<uint32_t>(ranges[c][i].second.size());
      ok = ok && emit(&sl, 4) && emit(ranges[c][i].first.data(), sl) &&
           emit(&el, 4) && emit(ranges[c][i].second.data(), el);
    }
  }
  uint32_t trailer = static_cast<uint32_t>(crc);
  ok = ok && write_all_fd(fd, &trailer, 4, err);
  ok = ok && fsync_fd(fd);
  close(fd);
  if (!ok) {
    unlink(tmp.c_str());
    if (err->empty()) *err = "run write failed";
    return false;
  }
  if (rename(tmp.c_str(), path.c_str()) != 0 || !fsync_dir(s->dir)) {
    *err = std::string("run rename: ") + strerror(errno);
    return false;
  }
  return true;
}

// mmap + validate + build the sparse index.
bool run_open(const std::string& path, Run* r, std::string* err) {
  r->path = path;
  r->fd = open(path.c_str(), O_RDONLY);
  if (r->fd < 0) {
    *err = std::string("run open: ") + strerror(errno);
    return false;
  }
  struct stat st;
  if (fstat(r->fd, &st) != 0 || st.st_size < 8) {
    *err = "run stat/short";
    return false;
  }
  r->map_len = static_cast<size_t>(st.st_size);
  r->map = static_cast<uint8_t*>(
      mmap(nullptr, r->map_len, PROT_READ, MAP_SHARED, r->fd, 0));
  if (r->map == MAP_FAILED) {
    *err = std::string("run mmap: ") + strerror(errno);
    return false;
  }
  if (memcmp(r->map, kRunMagic, 4) != 0) {
    *err = "run magic";
    return false;
  }
  size_t body_len = r->map_len - 8;
  uint32_t want = load_u32(r->map + 4 + body_len);
  if (crc32_of(r->map + 4, body_len) != want) {
    *err = "run crc";
    return false;
  }
  size_t off = 4, end = 4 + body_len;
  for (int c = 0; c < kNumCols; ++c) {
    auto need = [&](size_t n) { return off + n <= end; };
    if (!need(4)) { *err = "run truncated"; return false; }
    Run::ColIdx& ci = r->cols[c];
    ci.n_points = load_u32(r->map + off);
    off += 4;
    ci.points_off = off;
    for (uint32_t i = 0; i < ci.n_points; ++i) {
      if (!need(9)) { *err = "run truncated"; return false; }
      size_t e_off = off;
      uint32_t klen = load_u32(r->map + off + 1);
      if (!need(9 + klen)) { *err = "run truncated"; return false; }
      if (i % kIdxStride == 0) {
        ci.sparse.emplace_back(
            std::string(reinterpret_cast<const char*>(r->map + off + 5),
                        klen),
            e_off);
      }
      uint32_t vlen = load_u32(r->map + off + 5 + klen);
      off += 9 + klen + vlen;
      if (off > end) { *err = "run truncated"; return false; }
    }
    ci.points_end = off;
    if (!need(4)) { *err = "run truncated"; return false; }
    uint32_t nr = load_u32(r->map + off);
    off += 4;
    for (uint32_t i = 0; i < nr; ++i) {
      if (!need(4)) { *err = "run truncated"; return false; }
      uint32_t sl = load_u32(r->map + off);
      off += 4;
      if (!need(sl + 4)) { *err = "run truncated"; return false; }
      std::string sk(reinterpret_cast<const char*>(r->map + off), sl);
      off += sl;
      uint32_t el = load_u32(r->map + off);
      off += 4;
      if (!need(el)) { *err = "run truncated"; return false; }
      std::string ek(reinterpret_cast<const char*>(r->map + off), el);
      off += el;
      ci.ranges.emplace_back(std::move(sk), std::move(ek));
    }
  }
  return true;
}

// One point entry at `off`; returns its total size and the fields.
size_t run_point(const Run& r, size_t off, uint8_t* flag,
                 std::string_view* key, std::string_view* val) {
  *flag = r.map[off];
  uint32_t klen = load_u32(r.map + off + 1);
  *key = std::string_view(
      reinterpret_cast<const char*>(r.map + off + 5), klen);
  uint32_t vlen = load_u32(r.map + off + 5 + klen);
  *val = std::string_view(
      reinterpret_cast<const char*>(r.map + off + 9 + klen), vlen);
  return 9 + klen + vlen;
}

bool ranges_cover(const std::vector<std::pair<std::string, std::string>>& rs,
                  std::string_view key) {
  for (const auto& [s, e] : rs) {
    if (key >= s && (e.empty() || key < e)) return true;
  }
  return false;
}

bool manifest_rewrite(Store* s, std::string* err) {
  std::string body;
  for (const auto& r : s->runs) {
    // store basename only (dir may be moved)
    std::string base = r->path.substr(r->path.rfind('/') + 1);
    put_u32(&body, static_cast<uint32_t>(base.size()));
    body += base;
  }
  std::string tmp = s->manifest_path() + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) { *err = "manifest tmp"; return false; }
  bool ok = write_all_fd(fd, body.data(), body.size(), err) && fsync_fd(fd);
  close(fd);
  if (!ok) return false;
  if (rename(tmp.c_str(), s->manifest_path().c_str()) != 0 ||
      !fsync_dir(s->dir)) {
    *err = "manifest rename";
    return false;
  }
  return true;
}

bool manifest_load(Store* s, std::string* err) {
  FILE* f = fopen(s->manifest_path().c_str(), "rb");
  if (!f) return errno == ENOENT ? true : (*err = "manifest open", false);
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(static_cast<size_t>(size < 0 ? 0 : size));
  bool rok = buf.empty() ||
             fread(buf.data(), 1, buf.size(), f) == buf.size();
  fclose(f);
  if (!rok) { *err = "manifest read"; return false; }
  size_t off = 0;
  while (off + 4 <= buf.size()) {
    uint32_t sl = load_u32(buf.data() + off);
    off += 4;
    if (off + sl > buf.size()) { *err = "manifest truncated"; return false; }
    std::string name(reinterpret_cast<const char*>(buf.data() + off), sl);
    off += sl;
    auto run = std::make_unique<Run>();
    if (!run_open(s->dir + "/" + name, run.get(), err)) return false;
    // recover next_run_seq from names run_<seq>.sst
    uint32_t seq = static_cast<uint32_t>(
        strtoul(name.c_str() + 4, nullptr, 10));
    run->seq = seq;
    if (seq >= s->next_run_seq) s->next_run_seq = seq + 1;
    s->runs.push_back(std::move(run));
  }
  return true;
}

// -- merged reads (memtable -> runs newest-first) ---------------------------

enum class Hit { kLive, kTomb, kMiss };

Hit mem_lookup(const Store* s, int col, const std::string& key,
               std::string* val) {
  auto it = s->cols[col].find(key);
  if (it != s->cols[col].end()) {
    *val = it->second;
    return Hit::kLive;
  }
  if (s->dead[col].count(key)) return Hit::kTomb;
  if (ranges_cover(s->range_dead[col], key)) return Hit::kTomb;
  return Hit::kMiss;
}

Hit run_lookup(const Run& r, int col, std::string_view key,
               std::string* val) {
  const Run::ColIdx& ci = r.cols[col];
  if (ci.n_points > 0 && !ci.sparse.empty() && key >= ci.sparse[0].first) {
    // last sparse anchor with anchor.key <= key
    size_t lo = 0, hi = ci.sparse.size();
    while (lo + 1 < hi) {
      size_t mid = (lo + hi) / 2;
      if (ci.sparse[mid].first <= key) lo = mid;
      else hi = mid;
    }
    size_t off = ci.sparse[lo].second;
    for (size_t i = 0; i < kIdxStride && off < ci.points_end; ++i) {
      uint8_t flag;
      std::string_view k, v;
      size_t sz = run_point(r, off, &flag, &k, &v);
      if (k == key) {
        if (flag == kPtTomb) return Hit::kTomb;
        val->assign(v.data(), v.size());
        return Hit::kLive;
      }
      if (k > key) break;
      off += sz;
    }
  }
  if (ranges_cover(ci.ranges, key)) return Hit::kTomb;
  return Hit::kMiss;
}

// merged point get; s->mu held.
Hit merged_get(const Store* s, int col, const std::string& key,
               std::string* val) {
  Hit h = mem_lookup(s, col, key, val);
  if (h != Hit::kMiss) return h;
  for (auto it = s->runs.rbegin(); it != s->runs.rend(); ++it) {
    h = run_lookup(**it, col, key, val);
    if (h != Hit::kMiss) return h;
  }
  return Hit::kMiss;
}

// -- merged scan cursors ----------------------------------------------------

struct Cursor {
  // era rank: higher = newer (memtable = INT_MAX)
  int rank = 0;
  bool valid = false;
  std::string_view key;
  std::string_view val;
  uint8_t flag = kPtLive;

  // mem era: forward mode walks [li, le); reverse mode walks (li, le]
  // BACKWARD with le as the exclusive top (current = prev(le))
  const Table* live = nullptr;
  const Table* dead = nullptr;
  Table::const_iterator li, le, di, de;
  std::string mem_key;  // owned copy for mem entries
  // run era
  const Run* run = nullptr;
  int col = 0;
  size_t off = 0, end_off = 0;
  // reverse support
  bool reverse = false;
  const std::vector<uint32_t>* offsets = nullptr;  // full (reverse only)
  size_t rev_i = 0;  // index+1 into offsets

  void load_mem() {
    bool lv, dv;
    if (!reverse) {
      lv = li != le;
      dv = di != de;
    } else {
      lv = le != li;  // non-empty window (li = low bound, le = top)
      dv = de != di;
    }
    if (!lv && !dv) { valid = false; return; }
    bool take_live;
    const std::string* k;
    const std::string* v = nullptr;
    if (!reverse) {
      take_live = lv && (!dv || li->first < di->first);
      k = take_live ? &li->first : &di->first;
      if (take_live) v = &li->second;
    } else {
      auto lp = lv ? std::prev(le) : Table::const_iterator();
      auto dp = dv ? std::prev(de) : Table::const_iterator();
      take_live = lv && (!dv || !(lp->first < dp->first));
      k = take_live ? &lp->first : &dp->first;
      if (take_live) v = &lp->second;
    }
    mem_key = *k;
    key = mem_key;
    if (take_live) { flag = kPtLive; val = *v; }
    else { flag = kPtTomb; val = {}; }
    valid = true;
  }

  void adv_mem() {
    if (!reverse) {
      bool lv = li != le, dv = di != de;
      bool take_live = lv && (!dv || li->first < di->first);
      if (take_live) ++li; else ++di;
    } else {
      bool lv = le != li, dv = de != di;
      auto lp = lv ? std::prev(le) : Table::const_iterator();
      auto dp = dv ? std::prev(de) : Table::const_iterator();
      bool take_live = lv && (!dv || !(lp->first < dp->first));
      if (take_live) --le; else --de;
    }
    load_mem();
  }

  void load_run() {
    if (!reverse) {
      if (off >= end_off) { valid = false; return; }
      run_point(*run, off, &flag, &key, &val);
    } else {
      if (rev_i == 0) { valid = false; return; }
      size_t o = (*offsets)[rev_i - 1];
      run_point(*run, o, &flag, &key, &val);
    }
    valid = true;
  }

  void adv_run() {
    if (!reverse) {
      uint8_t f;
      std::string_view k, v;
      off += run_point(*run, off, &f, &k, &v);
    } else {
      --rev_i;
    }
    load_run();
  }

  void advance() {
    if (live) adv_mem();
    else adv_run();
  }
};

const std::vector<uint32_t>& run_all_offsets(Run& r, int col) {
  Run::ColIdx& ci = r.cols[col];
  if (ci.all_offsets.empty() && ci.n_points > 0) {
    ci.all_offsets.reserve(ci.n_points);
    size_t off = ci.points_off;
    for (uint32_t i = 0; i < ci.n_points && off < ci.points_end; ++i) {
      ci.all_offsets.push_back(static_cast<uint32_t>(off));
      uint8_t f;
      std::string_view k, v;
      off += run_point(r, off, &f, &k, &v);
    }
  }
  return ci.all_offsets;
}

// Position a run cursor at the first point >= start (forward) or last
// point < end-bound (reverse uses all_offsets).
void run_seek(Run& r, int col, Cursor* c, std::string_view start,
              std::string_view end, bool reverse) {
  Run::ColIdx& ci = r.cols[col];
  c->run = &r;
  c->col = col;
  c->reverse = reverse;
  if (!reverse) {
    size_t off = ci.points_off;
    if (!start.empty() && !ci.sparse.empty() && start > ci.sparse[0].first) {
      size_t lo = 0, hi = ci.sparse.size();
      while (lo + 1 < hi) {
        size_t mid = (lo + hi) / 2;
        if (ci.sparse[mid].first <= start) lo = mid;
        else hi = mid;
      }
      off = ci.sparse[lo].second;
    }
    // linear skip to >= start
    while (off < ci.points_end) {
      uint8_t f;
      std::string_view k, v;
      size_t sz = run_point(r, off, &f, &k, &v);
      if (start.empty() || k >= start) break;
      off += sz;
    }
    c->off = off;
    c->end_off = ci.points_end;
    c->load_run();
    // clamp at end bound during merge (caller checks)
  } else {
    const auto& offs = run_all_offsets(r, col);
    // rev_i = count of points with key < end (end empty = all)
    size_t lo = 0, hi = offs.size();
    if (!end.empty()) {
      while (lo < hi) {
        size_t mid = (lo + hi) / 2;
        uint8_t f;
        std::string_view k, v;
        run_point(r, offs[mid], &f, &k, &v);
        if (k < end) lo = mid + 1;
        else hi = mid;
      }
      c->rev_i = lo;
    } else {
      c->rev_i = offs.size();
    }
    c->offsets = &offs;
    c->load_run();
  }
}

// The merged scan over memtable + runs with tombstone masking.
// emit(key, val) returns false to stop (limit reached).
template <typename Emit>
void merged_scan(Store* s, int col, const std::string& start,
                 const std::string& end, bool reverse, Emit emit) {
  std::vector<std::unique_ptr<Cursor>> curs;
  {  // memtable cursor (rank = runs.size())
    auto c = std::make_unique<Cursor>();
    c->rank = static_cast<int>(s->runs.size());
    c->live = &s->cols[col];
    c->dead = &s->dead[col];
    c->reverse = reverse;
    const Table& lv = s->cols[col];
    const Table& dd = s->dead[col];
    if (!reverse) {
      c->li = start.empty() ? lv.begin() : lv.lower_bound(start);
      c->le = lv.end();
      c->di = start.empty() ? dd.begin() : dd.lower_bound(start);
      c->de = dd.end();
    } else {
      // backward walk of [lower_bound(start), lower_bound(end)):
      // li/di = low bounds, le/de = exclusive tops (current = prev(top))
      c->li = start.empty() ? lv.begin() : lv.lower_bound(start);
      c->le = end.empty() ? lv.end() : lv.lower_bound(end);
      c->di = start.empty() ? dd.begin() : dd.lower_bound(start);
      c->de = end.empty() ? dd.end() : dd.lower_bound(end);
    }
    c->load_mem();
    curs.push_back(std::move(c));
  }
  for (size_t i = 0; i < s->runs.size(); ++i) {
    auto c = std::make_unique<Cursor>();
    c->rank = static_cast<int>(i);
    run_seek(*s->runs[i], col, c.get(), start, end, reverse);
    curs.push_back(std::move(c));
  }
  auto newer_masks = [&](int rank, std::string_view key) -> bool {
    // ranges of strictly newer eras mask `key`
    if (rank < static_cast<int>(s->runs.size()) &&
        ranges_cover(s->range_dead[col], key))
      return true;
    for (size_t i = static_cast<size_t>(rank) + 1; i < s->runs.size(); ++i) {
      if (ranges_cover(s->runs[i]->cols[col].ranges, key)) return true;
    }
    return false;
  };
  while (true) {
    // pick the smallest (forward) / largest (reverse) key among cursors
    Cursor* best = nullptr;
    for (auto& c : curs) {
      if (!c->valid) continue;
      // bound checks
      if (!reverse) {
        if (!end.empty() && c->key >= end) { c->valid = false; continue; }
      } else {
        if (!start.empty() && c->key < start) { c->valid = false; continue; }
      }
      if (best == nullptr) { best = c.get(); continue; }
      if (!reverse) {
        if (c->key < best->key ||
            (c->key == best->key && c->rank > best->rank))
          best = c.get();
      } else {
        if (c->key > best->key ||
            (c->key == best->key && c->rank > best->rank))
          best = c.get();
      }
    }
    if (best == nullptr) return;
    std::string cur_key(best->key);
    bool visible = best->flag == kPtLive && !newer_masks(best->rank, cur_key);
    if (visible) {
      if (!emit(cur_key, best->val)) return;
    }
    // advance every cursor standing at cur_key
    for (auto& c : curs) {
      while (c->valid && c->key == cur_key) c->advance();
    }
  }
}

// -- LSM spill & compaction -------------------------------------------------

bool wal_restart(Store* s, std::string* err) {
  if (ftruncate(s->wal_fd, 0) != 0 || lseek(s->wal_fd, 0, SEEK_SET) < 0 ||
      (s->sync && !fsync_fd(s->wal_fd))) {
    *err = std::string("wal restart: ") + strerror(errno);
    return false;
  }
  s->wal_bytes = 0;
  return true;
}

// Spill the memtable (live + tombstones + ranges) to a new run; s->mu held.
bool spill(Store* s, std::string* err) {
  char name[32];
  snprintf(name, sizeof(name), "run_%08u.sst", s->next_run_seq);
  std::string path = s->dir + "/" + name;
  if (!run_write(s, path, s->cols, s->dead, s->range_dead, err)) return false;
  auto run = std::make_unique<Run>();
  run->seq = s->next_run_seq;
  if (!run_open(path, run.get(), err)) return false;
  s->next_run_seq++;
  s->runs.push_back(std::move(run));
  if (!manifest_rewrite(s, err)) return false;
  for (int c = 0; c < kNumCols; ++c) {
    s->cols[c].clear();
    s->dead[c].clear();
    s->range_dead[c].clear();
  }
  s->mem_bytes = 0;
  // Durable-op order matters: the legacy checkpoint must be durably gone
  // BEFORE the WAL is truncated.  If we truncated first and crashed before
  // the unlink hit disk, reopen would ckpt_load the stale checkpoint into
  // the memtable (newest rank), shadowing newer values in the runs.  With
  // this order every crash window is consistent: ckpt+full-WAL replay
  // reproduces exactly the content just spilled to the run.
  if (unlink(s->ckpt_path().c_str()) == 0) {
    if (!fsync_dir(s->dir)) {
      *err = "spill: fsync dir after ckpt unlink";
      return false;
    }
  } else if (errno != ENOENT) {
    // an unremovable stale checkpoint would shadow the runs on reopen;
    // failing the spill keeps WAL + checkpoint consistent instead
    *err = std::string("spill: ckpt unlink: ") + strerror(errno);
    return false;
  }
  // memtable content is durable in the run: restart the WAL
  if (!wal_restart(s, err)) return false;
  s->compact_cv.notify_all();
  return true;
}

// Merge a CONTIGUOUS window of runs (oldest..newest within the window)
// into one run file.  `bottom` means the window starts at the store's
// oldest run: only then may tombstones (point + range) be dropped —
// anywhere else they must survive to keep masking runs below the
// window.  Runs are immutable and only the compactor removes them, so
// this reads without the mutex.
bool merge_runs_to_file(Store* s, const std::vector<Run*>& inputs,
                        const std::string& path, std::string* err,
                        bool bottom) {
  std::string tmp = path + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) { *err = "merge tmp open"; return false; }
  uLong crc = crc32(0L, Z_NULL, 0);
  bool ok = write_all_fd(fd, kRunMagic, 4, err);
  auto emit = [&](const void* p, size_t n) -> bool {
    crc = crc32(crc, static_cast<const Bytef*>(p), static_cast<uInt>(n));
    return write_all_fd(fd, p, n, err);
  };
  for (int c = 0; ok && c < kNumCols; ++c) {
    // two passes (count, then entries) — run files are modest and
    // mmap'd, so the double walk is cheap relative to the write
    for (int pass = 0; ok && pass < 2; ++pass) {
      uint32_t count = 0;
      std::vector<Cursor> curs(inputs.size());
      for (size_t i = 0; i < inputs.size(); ++i) {
        curs[i].rank = static_cast<int>(i);
        run_seek(*inputs[i], c, &curs[i], {}, {}, false);
      }
      auto newer_masks = [&](int rank, std::string_view key) {
        for (size_t i = static_cast<size_t>(rank) + 1; i < inputs.size();
             ++i) {
          if (ranges_cover(inputs[i]->cols[c].ranges, key)) return true;
        }
        return false;
      };
      while (ok) {
        Cursor* best = nullptr;
        for (auto& cu : curs) {
          if (!cu.valid) continue;
          if (best == nullptr || cu.key < best->key ||
              (cu.key == best->key && cu.rank > best->rank))
            best = &cu;
        }
        if (best == nullptr) break;
        std::string cur_key(best->key);
        // non-bottom merges keep the newest version even when it is a
        // tombstone: it still masks data in runs below the window
        bool keep = !newer_masks(best->rank, cur_key) &&
                    (best->flag == kPtLive || !bottom);
        if (keep) {
          if (pass == 0) {
            ++count;
          } else {
            uint8_t flag = best->flag;
            uint32_t klen = static_cast<uint32_t>(cur_key.size());
            uint32_t vlen = static_cast<uint32_t>(best->val.size());
            ok = emit(&flag, 1) && emit(&klen, 4) &&
                 emit(cur_key.data(), klen) && emit(&vlen, 4) &&
                 (vlen == 0 || emit(best->val.data(), vlen));
          }
        }
        for (auto& cu : curs) {
          while (cu.valid && cu.key == cur_key) cu.advance();
        }
      }
      if (pass == 0 && ok) ok = emit(&count, 4);
    }
    if (bottom) {
      uint32_t nr = 0;  // bottom merge: nothing older left to mask
      ok = ok && emit(&nr, 4);
    } else {
      // union of the window's range tombstones: after the merge they
      // mask exactly the runs below the window, as each input's did
      uint32_t nr = 0;
      for (const Run* r : inputs)
        nr += static_cast<uint32_t>(r->cols[c].ranges.size());
      ok = ok && emit(&nr, 4);
      for (const Run* r : inputs) {
        for (const auto& [rs, re] : r->cols[c].ranges) {
          uint32_t sl = static_cast<uint32_t>(rs.size());
          uint32_t el = static_cast<uint32_t>(re.size());
          ok = ok && emit(&sl, 4) && emit(rs.data(), sl) &&
               emit(&el, 4) && emit(re.data(), el);
          if (!ok) break;
        }
        if (!ok) break;
      }
    }
  }
  uint32_t trailer = static_cast<uint32_t>(crc);
  ok = ok && write_all_fd(fd, &trailer, 4, err);
  ok = ok && fsync_fd(fd);
  close(fd);
  if (!ok) { unlink(tmp.c_str()); return false; }
  if (rename(tmp.c_str(), path.c_str()) != 0 || !fsync_dir(s->dir)) {
    *err = "merge rename";
    return false;
  }
  return true;
}

void compactor_main(Store* s) {
  std::unique_lock<std::mutex> lk(s->mu);
  while (!s->stopping) {
    if (static_cast<int64_t>(s->runs.size()) <= s->max_runs) {
      s->compact_cv.wait(lk);
      continue;
    }
    // Size-tiered pick-K (VERDICT r2 #7): merge the cheapest CONTIGUOUS
    // window of K runs (contiguity preserves rank order — newer masks
    // older) instead of merge-all, so compaction I/O per cycle tracks
    // the small spill tier, not total store size.  K restores the run
    // count to max_runs; min-total-bytes picks the fresh small spills
    // over the big bottom run until tiers grow comparable.
    size_t n = s->runs.size();
    size_t k = n - static_cast<size_t>(s->max_runs) + 1;
    size_t win = 0;
    int64_t best_bytes = -1;
    for (size_t i = 0; i + k <= n; ++i) {
      int64_t b = 0;
      for (size_t j = i; j < i + k; ++j)
        b += static_cast<int64_t>(s->runs[j]->map_len);
      if (best_bytes < 0 || b < best_bytes) {
        best_bytes = b;
        win = i;
      }
    }
    bool bottom = win == 0;  // only a bottom merge may drop tombstones
    std::vector<Run*> inputs;
    for (size_t j = win; j < win + k; ++j)
      inputs.push_back(s->runs[j].get());
    uint32_t seq = s->next_run_seq++;
    s->compact_running = true;
    lk.unlock();
    char name[32];
    snprintf(name, sizeof(name), "run_%08u.sst", seq);
    std::string path = s->dir + "/" + name;
    std::string err;
    auto merged = std::make_unique<Run>();
    bool ok = merge_runs_to_file(s, inputs, path, &err, bottom) &&
              run_open(path, merged.get(), &err);
    merged->seq = seq;
    lk.lock();
    s->compact_running = false;
    if (!ok) {
      fprintf(stderr, "tpuraft-kvstore: compaction failed: %s\n",
              err.c_str());
      unlink(path.c_str());
      // back off until the next spill wakes us; bounded wait so a
      // stopping flag set while we merged can't strand tkv_close
      // (the notify may have fired before this wait began)
      if (!s->stopping) s->compact_cv.wait_for(lk, std::chrono::seconds(1));
      continue;
    }
    s->compactions++;
    s->compact_input_bytes += best_bytes;
    s->compact_last_input_bytes = best_bytes;
    // swap the window for the merged run; spills during the merge only
    // APPENDED (newer), so indexes [win, win+k) are still the inputs
    std::vector<std::string> old_paths;
    for (size_t j = win; j < win + k; ++j)
      old_paths.push_back(s->runs[j]->path);
    s->runs.erase(s->runs.begin() + win, s->runs.begin() + win + k);
    s->runs.insert(s->runs.begin() + win, std::move(merged));
    if (!manifest_rewrite(s, &err)) {
      // KEEP the old files: the durable manifest still references
      // them, and deleting would make the store unopenable after a
      // crash.  They leak until the next successful rewrite (any
      // spill), which then lists the merged run instead.
      fprintf(stderr, "tpuraft-kvstore: manifest rewrite failed (%s); "
              "retaining pre-compaction run files\n", err.c_str());
    } else {
      for (const auto& p : old_paths) unlink(p.c_str());
    }
  }
}

// -- WAL --------------------------------------------------------------------

bool wal_append(Store* s, const uint8_t* payload, size_t n, std::string* err) {
  std::string rec;
  rec.reserve(8 + n);
  put_u32(&rec, static_cast<uint32_t>(n));
  put_u32(&rec, crc32_of(payload, n));
  rec.append(reinterpret_cast<const char*>(payload), n);
  const char* p = rec.data();
  size_t left = rec.size();
  while (left > 0) {
    ssize_t w = write(s->wal_fd, p, left);
    if (w < 0) {
      if (errno == EINTR) continue;
      *err = std::string("wal write: ") + strerror(errno);
      return false;
    }
    p += w;
    left -= static_cast<size_t>(w);
  }
  if (s->sync && !fsync_fd(s->wal_fd)) {
    *err = std::string("wal fsync: ") + strerror(errno);
    return false;
  }
  s->wal_bytes += static_cast<int64_t>(rec.size());
  return true;
}

// Replays wal.log over the tables; stops cleanly at a torn tail.
bool wal_replay(Store* s, std::string* err) {
  FILE* f = fopen(s->wal_path().c_str(), "rb");
  if (!f) {
    if (errno == ENOENT) return true;
    *err = std::string("wal open: ") + strerror(errno);
    return false;
  }
  fseek(f, 0, SEEK_END);
  int64_t file_size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (file_size < 0) {
    // can't size the file: a bookkeeping failure must not become data
    // loss via the truncate below
    fclose(f);
    *err = std::string("wal size probe: ") + strerror(errno);
    return false;
  }
  std::vector<uint8_t> buf;
  int64_t valid_end = 0;
  for (;;) {
    uint8_t hdr[8];
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t len = load_u32(hdr), crc = load_u32(hdr + 4);
    // The header is not self-checksummed: clamp the length field against
    // the bytes actually present so a corrupted tail can't trigger a
    // giant allocation — anything oversized is by definition torn.
    if (static_cast<int64_t>(len) > file_size - valid_end - 8) break;
    buf.resize(len);
    if (len > 0 && fread(buf.data(), 1, len, f) != len) break;
    if (crc32_of(buf.data(), len) != crc) break;
    std::vector<std::tuple<uint8_t, uint8_t, std::string, std::string>> ops;
    if (!parse_ops(buf.data(), len, &ops)) break;
    apply_ops(s, ops);
    valid_end += 8 + static_cast<int64_t>(len);
  }
  fclose(f);
  // drop the torn tail so future appends never sit after garbage
  if (truncate(s->wal_path().c_str(), valid_end) != 0 && errno != ENOENT) {
    *err = std::string("wal truncate: ") + strerror(errno);
    return false;
  }
  s->wal_bytes = valid_end;
  return true;
}

// -- checkpoint -------------------------------------------------------------

bool ckpt_load(Store* s, std::string* err) {
  FILE* f = fopen(s->ckpt_path().c_str(), "rb");
  if (!f) {
    if (errno == ENOENT) return true;
    *err = std::string("checkpoint open: ") + strerror(errno);
    return false;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (size < 8) {
    fclose(f);
    *err = "checkpoint too short";
    return false;
  }
  std::vector<uint8_t> blob(static_cast<size_t>(size));
  bool read_ok = fread(blob.data(), 1, blob.size(), f) == blob.size();
  fclose(f);
  if (!read_ok || memcmp(blob.data(), kCkptMagic, 4) != 0) {
    *err = "checkpoint magic/read failure";
    return false;
  }
  size_t body_len = blob.size() - 8;
  uint32_t want = load_u32(blob.data() + 4 + body_len);
  if (crc32_of(blob.data() + 4, body_len) != want) {
    *err = "checkpoint crc mismatch";
    return false;
  }
  size_t off = 4;
  for (int c = 0; c < kNumCols; ++c) {
    if (off + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
    uint32_t count = load_u32(blob.data() + off);
    off += 4;
    auto hint = s->cols[c].end();
    for (uint32_t i = 0; i < count; ++i) {
      if (off + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      uint32_t klen = load_u32(blob.data() + off);
      off += 4;
      if (off + klen + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      std::string key(reinterpret_cast<const char*>(blob.data() + off), klen);
      off += klen;
      uint32_t vlen = load_u32(blob.data() + off);
      off += 4;
      if (off + vlen > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      std::string val(reinterpret_cast<const char*>(blob.data() + off), vlen);
      off += vlen;
      // checkpoint is written in order: amortized O(1) insertion at end
      hint = s->cols[c].emplace_hint(hint, std::move(key), std::move(val));
    }
  }
  return true;
}

bool ckpt_write(Store* s, std::string* err) {
  std::string body;
  for (int c = 0; c < kNumCols; ++c) {
    put_u32(&body, static_cast<uint32_t>(s->cols[c].size()));
    for (const auto& [k, v] : s->cols[c]) {
      put_u32(&body, static_cast<uint32_t>(k.size()));
      body += k;
      put_u32(&body, static_cast<uint32_t>(v.size()));
      body += v;
    }
  }
  std::string tmp = s->ckpt_path() + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    *err = std::string("checkpoint tmp open: ") + strerror(errno);
    return false;
  }
  // Stream magic, body, CRC trailer — no concatenated second copy of the
  // dataset while the store mutex is held.
  char trailer[4];
  uint32_t crc = crc32_of(body.data(), body.size());
  memcpy(trailer, &crc, 4);  // native-endian, matching load_u32
  const std::pair<const char*, size_t> parts[] = {
      {kCkptMagic, 4}, {body.data(), body.size()}, {trailer, 4}};
  bool ok = true;
  for (const auto& [p0, n0] : parts) {
    const char* p = p0;
    size_t left = n0;
    while (ok && left > 0) {
      ssize_t w = write(fd, p, left);
      if (w < 0) {
        if (errno == EINTR) continue;
        ok = false;
        break;
      }
      p += w;
      left -= static_cast<size_t>(w);
    }
    if (!ok) break;
  }
  ok = ok && fsync_fd(fd);
  close(fd);
  if (!ok) {
    *err = std::string("checkpoint write: ") + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  if (rename(tmp.c_str(), s->ckpt_path().c_str()) != 0 ||
      !fsync_dir(s->dir)) {
    *err = std::string("checkpoint rename: ") + strerror(errno);
    return false;
  }
  // the checkpoint now covers everything: restart the WAL
  if (ftruncate(s->wal_fd, 0) != 0 ||
      lseek(s->wal_fd, 0, SEEK_SET) < 0 ||
      (s->sync && !fsync_fd(s->wal_fd))) {
    *err = std::string("wal restart: ") + strerror(errno);
    return false;
  }
  s->wal_bytes = 0;
  s->ckpt_retry_floor = 0;  // any successful checkpoint clears the backoff
  return true;
}

// Auto-checkpoint if the WAL has grown past the threshold.  A checkpoint
// failure is NOT a write failure: by this point the op is fsynced in the
// WAL and applied, so it must be reported durable.  Replay is idempotent
// (put/delete/delete-range), so even a rename-then-truncate-failed half
// checkpoint recovers correctly.  On failure, back off: don't retry the
// full O(n) serialization until the WAL grows by another threshold.
void maybe_ckpt(Store* s) {
  if (s->ckpt_wal_bytes <= 0 || s->wal_bytes < s->ckpt_wal_bytes) return;
  if (s->wal_bytes < s->ckpt_retry_floor) return;
  std::string cerr;
  if (!ckpt_write(s, &cerr)) {
    s->ckpt_retry_floor = s->wal_bytes + s->ckpt_wal_bytes;
    fprintf(stderr, "tpuraft-kvstore: auto-checkpoint failed (%s); "
            "will retry after %lld more WAL bytes\n",
            cerr.c_str(), static_cast<long long>(s->ckpt_wal_bytes));
  } else {
    s->ckpt_retry_floor = 0;
  }
}

// One durable write: WAL first, then tables, then maybe spill/checkpoint.
bool do_write(Store* s, const uint8_t* payload, size_t n, std::string* err) {
  std::vector<std::tuple<uint8_t, uint8_t, std::string, std::string>> ops;
  if (!parse_ops(payload, n, &ops)) {
    *err = "malformed op stream";
    return false;
  }
  if (!wal_append(s, payload, n, err)) return false;
  apply_ops(s, ops);
  if (s->lsm()) {
    if (s->mem_bytes >= s->memtable_budget) {
      std::string serr;
      if (!spill(s, &serr)) {
        // like a failed auto-checkpoint: the op IS durable (WAL),
        // report success and retry the spill on later writes
        fprintf(stderr, "tpuraft-kvstore: spill failed (%s); retrying "
                "on later writes\n", serr.c_str());
      }
    }
  } else {
    maybe_ckpt(s);
  }
  return true;
}

uint8_t* copy_out(const std::string& data) {
  uint8_t* out = static_cast<uint8_t*>(malloc(data.size() ? data.size() : 1));
  if (out) memcpy(out, data.data(), data.size());
  return out;
}

}  // namespace

extern "C" {

// LSM-capable open (VERDICT r1 #7): memtable_budget_bytes > 0 enables
// sorted-run spill + background compaction; 0 keeps the legacy
// memtable+checkpoint engine bit-for-bit.
void* tkv_open2(const char* dir, int sync, int64_t ckpt_wal_bytes,
                int64_t memtable_budget_bytes, int64_t max_runs,
                char* err, int errlen) {
  auto s = std::make_unique<Store>();
  s->dir = dir;
  s->sync = sync != 0;
  if (ckpt_wal_bytes > 0) s->ckpt_wal_bytes = ckpt_wal_bytes;
  if (memtable_budget_bytes > 0) s->memtable_budget = memtable_budget_bytes;
  if (max_runs > 1) s->max_runs = max_runs;
  if (mkdir(dir, 0755) != 0 && errno != EEXIST) {
    set_err(err, errlen, std::string("mkdir: ") + strerror(errno));
    return nullptr;
  }
  std::string msg;
  if (!s->lsm()) {
    // Guard against opening an LSM-tiered directory without LSM params
    // (legacy tkv_open or a config downgrade): the manifest's runs would
    // be silently invisible — reads miss most of the dataset and the next
    // checkpoint durably excludes it.  Fail loudly instead.
    struct stat st;
    if (stat(s->manifest_path().c_str(), &st) == 0) {
      set_err(err, errlen,
              "LSM directory (manifest present) opened without LSM params; "
              "reopen with memtable_budget_bytes > 0 (tkv_open2)");
      return nullptr;
    }
  }
  if (s->lsm() && !manifest_load(s.get(), &msg)) {
    set_err(err, errlen, msg);
    return nullptr;
  }
  // legacy checkpoint (pre-LSM dirs / mode downgrade): becomes the
  // initial memtable; the next spill converts it to a run
  if (!ckpt_load(s.get(), &msg) || !wal_replay(s.get(), &msg)) {
    set_err(err, errlen, msg);
    return nullptr;
  }
  if (s->lsm()) {
    // full recount: wal_replay's apply_ops already accounted its part,
    // so summing on top would double-count and trigger premature spills
    s->mem_bytes = 0;
    for (int c = 0; c < kNumCols; ++c) {
      for (const auto& [k, v] : s->cols[c])
        s->mem_bytes += static_cast<int64_t>(k.size() + v.size());
      for (const auto& [k, v] : s->dead[c])
        s->mem_bytes += static_cast<int64_t>(k.size());
      for (const auto& [a, b] : s->range_dead[c])
        s->mem_bytes += static_cast<int64_t>(a.size() + b.size());
    }
  }
  s->wal_fd = open(s->wal_path().c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (s->wal_fd < 0) {
    set_err(err, errlen, std::string("wal open: ") + strerror(errno));
    return nullptr;
  }
  if (s->lsm()) {
    Store* sp = s.get();
    s->compactor = std::thread([sp] { compactor_main(sp); });
  }
  return s.release();
}

void* tkv_open(const char* dir, int sync, int64_t ckpt_wal_bytes,
               char* err, int errlen) {
  return tkv_open2(dir, sync, ckpt_wal_bytes, 0, 0, err, errlen);
}

void tkv_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return;
  if (s->compactor.joinable()) {
    {
      std::lock_guard<std::mutex> g(s->mu);
      s->stopping = true;
    }
    s->compact_cv.notify_all();
    s->compactor.join();
  }
  if (s->wal_fd >= 0) close(s->wal_fd);
  delete s;
}

int64_t tkv_run_count(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<int64_t>(s->runs.size());
}

int64_t tkv_mem_bytes(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->mem_bytes;
}

int64_t tkv_compactions(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->compactions;
}

int64_t tkv_compact_input_bytes(void* h) {
  // cumulative input bytes across all compaction cycles (write
  // amplification accounting)
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->compact_input_bytes;
}

int64_t tkv_compact_last_input_bytes(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->compact_last_input_bytes;
}

int64_t tkv_data_bytes(void* h) {
  // total bytes across run files (the on-disk LSM footprint)
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  int64_t total = 0;
  for (const auto& r : s->runs) total += static_cast<int64_t>(r->map_len);
  return total;
}

void tkv_free(uint8_t* p) { free(p); }

int tkv_apply_batch(void* h, const uint8_t* ops, int64_t len,
                    char* err, int errlen) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string msg;
  if (!do_write(s, ops, static_cast<size_t>(len), &msg)) {
    set_err(err, errlen, msg);
    return -1;
  }
  return 0;
}

int64_t tkv_get(void* h, int col, const uint8_t* k, int64_t kl,
                uint8_t** out) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string key(reinterpret_cast<const char*>(k), kl);
  std::string val;
  if (merged_get(s, col, key, &val) != Hit::kLive) return -1;
  *out = copy_out(val);
  return static_cast<int64_t>(val.size());
}

// Packed result: u32 count | repeated (u32 klen key [u32 vlen val]).
// with_values=0 omits values. reverse=1 returns descending order.
// limit<0 means unbounded.
int64_t tkv_scan(void* h, int col, const uint8_t* start, int64_t sl,
                 const uint8_t* end, int64_t el, int64_t limit,
                 int with_values, int reverse, uint8_t** out) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string skey(reinterpret_cast<const char*>(start), sl);
  std::string ekey(reinterpret_cast<const char*>(end), el);
  std::string body;
  uint32_t count = 0;
  merged_scan(s, col, skey, ekey, reverse != 0,
              [&](const std::string& k, std::string_view v) {
    if (limit >= 0 && count >= static_cast<uint64_t>(limit)) return false;
    put_u32(&body, static_cast<uint32_t>(k.size()));
    body += k;
    if (with_values) {
      put_u32(&body, static_cast<uint32_t>(v.size()));
      body.append(v.data(), v.size());
    }
    ++count;
    return true;
  });
  std::string packed;
  packed.reserve(4 + body.size());
  put_u32(&packed, count);
  packed += body;
  *out = copy_out(packed);
  return static_cast<int64_t>(packed.size());
}

int64_t tkv_count_range(void* h, int col, const uint8_t* start, int64_t sl,
                        const uint8_t* end, int64_t el) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string skey(reinterpret_cast<const char*>(start), sl);
  std::string ekey(reinterpret_cast<const char*>(end), el);
  if (!s->lsm()) {
    Table& t = s->cols[col];
    auto lo = skey.empty() ? t.begin() : t.lower_bound(skey);
    auto hi = ekey.empty() ? t.end() : t.lower_bound(ekey);
    return static_cast<int64_t>(std::distance(lo, hi));
  }
  int64_t n = 0;
  merged_scan(s, col, skey, ekey, false,
              [&](const std::string&, std::string_view) {
    ++n;
    return true;
  });
  return n;
}

int tkv_checkpoint(void* h, char* err, int errlen) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string msg;
  // LSM mode: "checkpoint" = flush the memtable to a run (WAL resets
  // either way; recovery stays O(memtable))
  bool ok = s->lsm() ? spill(s, &msg) : ckpt_write(s, &msg);
  if (!ok) {
    set_err(err, errlen, msg);
    return -1;
  }
  return 0;
}

int64_t tkv_wal_bytes(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->wal_bytes;
}

int64_t tkv_count(void* h, int col) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  if (!s->lsm()) return static_cast<int64_t>(s->cols[col].size());
  int64_t n = 0;  // LSM: merged live count (O(dataset) walk — stats use)
  merged_scan(s, col, std::string(), std::string(), false,
              [&](const std::string&, std::string_view) {
    ++n;
    return true;
  });
  return n;
}

}  // extern "C"
