// tpuraft native KV storage engine.
//
// Reference parity: the role RocksDB (C++, via rocksdbjni) plays under
// rhea:storage/RocksRawKVStore — the durable ordered-KV engine shared by
// every RegionEngine of a process (SURVEY.md §3.2/§3.4).  Where the
// reference leans on a general-purpose LSM, this engine is purpose-built
// for RheaKV's access pattern — point ops + range scans from a
// single-writer state-machine thread, with recovery bounded by a
// checkpoint: an ordered in-memory table per column, a CRC-framed
// write-ahead log for durability, and an atomic sorted checkpoint that
// truncates the WAL when it grows past a threshold.
//
// Columns (fixed): 0=data 1=sequence 2=lock 3=meta.  Column semantics
// (what a sequence/lock value means) live in the Python wrapper
// (tpuraft/rheakv/native_store.py) — apply-time logic is single-threaded
// through the raft state machine, so read-modify-write up there is safe.
//
// On-disk layout under the store dir:
//   wal.log     repeated [ u32le len | u32le crc32(payload) | payload ]
//               payload = 1+ ops: op(1) col(1) klen(4) key vlen(4) val
//               op: 1=put 2=delete 3=delete_range(key=start, val=end)
//               One record per write call -> each call is atomic; a torn
//               tail (short frame or CRC mismatch) is dropped on replay.
//   checkpoint  magic "TKV1" | per col: u32 count, (klen key vlen val)* |
//               u32 crc32(everything after magic)
//               written tmp+fsync+rename+dirsync, then the WAL truncates.
//
// Exposed as a C ABI for ctypes.  All returned buffers are malloc'd and
// released with tkv_free.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <tuple>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

constexpr int kNumCols = 4;
constexpr char kCkptMagic[4] = {'T', 'K', 'V', '1'};
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDelete = 2;
constexpr uint8_t kOpDeleteRange = 3;
constexpr int64_t kDefaultCkptWalBytes = 64LL << 20;

uint32_t load_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
void put_u32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

uint32_t crc32_of(const void* data, size_t n) {
  return static_cast<uint32_t>(
      crc32(0L, static_cast<const Bytef*>(data), static_cast<uInt>(n)));
}

bool fsync_fd(int fd) { return fsync(fd) == 0; }

bool fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = fsync(fd) == 0;
  close(fd);
  return ok;
}

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

using Table = std::map<std::string, std::string>;

struct Store {
  std::mutex mu;
  std::string dir;
  Table cols[kNumCols];
  int wal_fd = -1;
  int64_t wal_bytes = 0;
  bool sync = true;
  int64_t ckpt_wal_bytes = kDefaultCkptWalBytes;
  int64_t ckpt_retry_floor = 0;  // backoff marker after a failed auto-ckpt

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string ckpt_path() const { return dir + "/checkpoint"; }
};

// -- op encoding shared by WAL records and tkv_apply_batch ------------------

// Validates and applies one op stream to the tables. Returns false on a
// malformed stream (nothing about partial application matters to callers:
// WAL replay treats malformed == torn tail, and tkv_apply_batch validates
// before applying).
bool parse_ops(const uint8_t* p, size_t n,
               std::vector<std::tuple<uint8_t, uint8_t, std::string,
                                      std::string>>* out) {
  size_t off = 0;
  while (off < n) {
    if (off + 2 + 4 > n) return false;
    uint8_t op = p[off], col = p[off + 1];
    off += 2;
    if (op < kOpPut || op > kOpDeleteRange || col >= kNumCols) return false;
    uint32_t klen = load_u32(p + off);
    off += 4;
    if (off + klen + 4 > n) return false;
    std::string key(reinterpret_cast<const char*>(p + off), klen);
    off += klen;
    uint32_t vlen = load_u32(p + off);
    off += 4;
    if (off + vlen > n) return false;
    std::string val(reinterpret_cast<const char*>(p + off), vlen);
    off += vlen;
    out->emplace_back(op, col, std::move(key), std::move(val));
  }
  return true;
}

void apply_ops(Store* s,
               const std::vector<std::tuple<uint8_t, uint8_t, std::string,
                                            std::string>>& ops) {
  for (const auto& [op, col, key, val] : ops) {
    Table& t = s->cols[col];
    switch (op) {
      case kOpPut:
        t[key] = val;
        break;
      case kOpDelete:
        t.erase(key);
        break;
      case kOpDeleteRange: {
        auto lo = key.empty() ? t.begin() : t.lower_bound(key);
        auto hi = val.empty() ? t.end() : t.lower_bound(val);
        t.erase(lo, hi);
        break;
      }
    }
  }
}

// -- WAL --------------------------------------------------------------------

bool wal_append(Store* s, const uint8_t* payload, size_t n, std::string* err) {
  std::string rec;
  rec.reserve(8 + n);
  put_u32(&rec, static_cast<uint32_t>(n));
  put_u32(&rec, crc32_of(payload, n));
  rec.append(reinterpret_cast<const char*>(payload), n);
  const char* p = rec.data();
  size_t left = rec.size();
  while (left > 0) {
    ssize_t w = write(s->wal_fd, p, left);
    if (w < 0) {
      if (errno == EINTR) continue;
      *err = std::string("wal write: ") + strerror(errno);
      return false;
    }
    p += w;
    left -= static_cast<size_t>(w);
  }
  if (s->sync && !fsync_fd(s->wal_fd)) {
    *err = std::string("wal fsync: ") + strerror(errno);
    return false;
  }
  s->wal_bytes += static_cast<int64_t>(rec.size());
  return true;
}

// Replays wal.log over the tables; stops cleanly at a torn tail.
bool wal_replay(Store* s, std::string* err) {
  FILE* f = fopen(s->wal_path().c_str(), "rb");
  if (!f) {
    if (errno == ENOENT) return true;
    *err = std::string("wal open: ") + strerror(errno);
    return false;
  }
  fseek(f, 0, SEEK_END);
  int64_t file_size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (file_size < 0) {
    // can't size the file: a bookkeeping failure must not become data
    // loss via the truncate below
    fclose(f);
    *err = std::string("wal size probe: ") + strerror(errno);
    return false;
  }
  std::vector<uint8_t> buf;
  int64_t valid_end = 0;
  for (;;) {
    uint8_t hdr[8];
    if (fread(hdr, 1, 8, f) != 8) break;
    uint32_t len = load_u32(hdr), crc = load_u32(hdr + 4);
    // The header is not self-checksummed: clamp the length field against
    // the bytes actually present so a corrupted tail can't trigger a
    // giant allocation — anything oversized is by definition torn.
    if (static_cast<int64_t>(len) > file_size - valid_end - 8) break;
    buf.resize(len);
    if (len > 0 && fread(buf.data(), 1, len, f) != len) break;
    if (crc32_of(buf.data(), len) != crc) break;
    std::vector<std::tuple<uint8_t, uint8_t, std::string, std::string>> ops;
    if (!parse_ops(buf.data(), len, &ops)) break;
    apply_ops(s, ops);
    valid_end += 8 + static_cast<int64_t>(len);
  }
  fclose(f);
  // drop the torn tail so future appends never sit after garbage
  if (truncate(s->wal_path().c_str(), valid_end) != 0 && errno != ENOENT) {
    *err = std::string("wal truncate: ") + strerror(errno);
    return false;
  }
  s->wal_bytes = valid_end;
  return true;
}

// -- checkpoint -------------------------------------------------------------

bool ckpt_load(Store* s, std::string* err) {
  FILE* f = fopen(s->ckpt_path().c_str(), "rb");
  if (!f) {
    if (errno == ENOENT) return true;
    *err = std::string("checkpoint open: ") + strerror(errno);
    return false;
  }
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  if (size < 8) {
    fclose(f);
    *err = "checkpoint too short";
    return false;
  }
  std::vector<uint8_t> blob(static_cast<size_t>(size));
  bool read_ok = fread(blob.data(), 1, blob.size(), f) == blob.size();
  fclose(f);
  if (!read_ok || memcmp(blob.data(), kCkptMagic, 4) != 0) {
    *err = "checkpoint magic/read failure";
    return false;
  }
  size_t body_len = blob.size() - 8;
  uint32_t want = load_u32(blob.data() + 4 + body_len);
  if (crc32_of(blob.data() + 4, body_len) != want) {
    *err = "checkpoint crc mismatch";
    return false;
  }
  size_t off = 4;
  for (int c = 0; c < kNumCols; ++c) {
    if (off + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
    uint32_t count = load_u32(blob.data() + off);
    off += 4;
    auto hint = s->cols[c].end();
    for (uint32_t i = 0; i < count; ++i) {
      if (off + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      uint32_t klen = load_u32(blob.data() + off);
      off += 4;
      if (off + klen + 4 > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      std::string key(reinterpret_cast<const char*>(blob.data() + off), klen);
      off += klen;
      uint32_t vlen = load_u32(blob.data() + off);
      off += 4;
      if (off + vlen > 4 + body_len) { *err = "checkpoint truncated"; return false; }
      std::string val(reinterpret_cast<const char*>(blob.data() + off), vlen);
      off += vlen;
      // checkpoint is written in order: amortized O(1) insertion at end
      hint = s->cols[c].emplace_hint(hint, std::move(key), std::move(val));
    }
  }
  return true;
}

bool ckpt_write(Store* s, std::string* err) {
  std::string body;
  for (int c = 0; c < kNumCols; ++c) {
    put_u32(&body, static_cast<uint32_t>(s->cols[c].size()));
    for (const auto& [k, v] : s->cols[c]) {
      put_u32(&body, static_cast<uint32_t>(k.size()));
      body += k;
      put_u32(&body, static_cast<uint32_t>(v.size()));
      body += v;
    }
  }
  std::string tmp = s->ckpt_path() + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    *err = std::string("checkpoint tmp open: ") + strerror(errno);
    return false;
  }
  // Stream magic, body, CRC trailer — no concatenated second copy of the
  // dataset while the store mutex is held.
  char trailer[4];
  uint32_t crc = crc32_of(body.data(), body.size());
  memcpy(trailer, &crc, 4);  // native-endian, matching load_u32
  const std::pair<const char*, size_t> parts[] = {
      {kCkptMagic, 4}, {body.data(), body.size()}, {trailer, 4}};
  bool ok = true;
  for (const auto& [p0, n0] : parts) {
    const char* p = p0;
    size_t left = n0;
    while (ok && left > 0) {
      ssize_t w = write(fd, p, left);
      if (w < 0) {
        if (errno == EINTR) continue;
        ok = false;
        break;
      }
      p += w;
      left -= static_cast<size_t>(w);
    }
    if (!ok) break;
  }
  ok = ok && fsync_fd(fd);
  close(fd);
  if (!ok) {
    *err = std::string("checkpoint write: ") + strerror(errno);
    unlink(tmp.c_str());
    return false;
  }
  if (rename(tmp.c_str(), s->ckpt_path().c_str()) != 0 ||
      !fsync_dir(s->dir)) {
    *err = std::string("checkpoint rename: ") + strerror(errno);
    return false;
  }
  // the checkpoint now covers everything: restart the WAL
  if (ftruncate(s->wal_fd, 0) != 0 ||
      lseek(s->wal_fd, 0, SEEK_SET) < 0 ||
      (s->sync && !fsync_fd(s->wal_fd))) {
    *err = std::string("wal restart: ") + strerror(errno);
    return false;
  }
  s->wal_bytes = 0;
  s->ckpt_retry_floor = 0;  // any successful checkpoint clears the backoff
  return true;
}

// Auto-checkpoint if the WAL has grown past the threshold.  A checkpoint
// failure is NOT a write failure: by this point the op is fsynced in the
// WAL and applied, so it must be reported durable.  Replay is idempotent
// (put/delete/delete-range), so even a rename-then-truncate-failed half
// checkpoint recovers correctly.  On failure, back off: don't retry the
// full O(n) serialization until the WAL grows by another threshold.
void maybe_ckpt(Store* s) {
  if (s->ckpt_wal_bytes <= 0 || s->wal_bytes < s->ckpt_wal_bytes) return;
  if (s->wal_bytes < s->ckpt_retry_floor) return;
  std::string cerr;
  if (!ckpt_write(s, &cerr)) {
    s->ckpt_retry_floor = s->wal_bytes + s->ckpt_wal_bytes;
    fprintf(stderr, "tpuraft-kvstore: auto-checkpoint failed (%s); "
            "will retry after %lld more WAL bytes\n",
            cerr.c_str(), static_cast<long long>(s->ckpt_wal_bytes));
  } else {
    s->ckpt_retry_floor = 0;
  }
}

// One durable write: WAL first, then tables, then maybe checkpoint.
bool do_write(Store* s, const uint8_t* payload, size_t n, std::string* err) {
  std::vector<std::tuple<uint8_t, uint8_t, std::string, std::string>> ops;
  if (!parse_ops(payload, n, &ops)) {
    *err = "malformed op stream";
    return false;
  }
  if (!wal_append(s, payload, n, err)) return false;
  apply_ops(s, ops);
  maybe_ckpt(s);
  return true;
}

uint8_t* copy_out(const std::string& data) {
  uint8_t* out = static_cast<uint8_t*>(malloc(data.size() ? data.size() : 1));
  if (out) memcpy(out, data.data(), data.size());
  return out;
}

}  // namespace

extern "C" {

void* tkv_open(const char* dir, int sync, int64_t ckpt_wal_bytes,
               char* err, int errlen) {
  auto s = std::make_unique<Store>();
  s->dir = dir;
  s->sync = sync != 0;
  if (ckpt_wal_bytes > 0) s->ckpt_wal_bytes = ckpt_wal_bytes;
  if (mkdir(dir, 0755) != 0 && errno != EEXIST) {
    set_err(err, errlen, std::string("mkdir: ") + strerror(errno));
    return nullptr;
  }
  std::string msg;
  if (!ckpt_load(s.get(), &msg) || !wal_replay(s.get(), &msg)) {
    set_err(err, errlen, msg);
    return nullptr;
  }
  s->wal_fd = open(s->wal_path().c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (s->wal_fd < 0) {
    set_err(err, errlen, std::string("wal open: ") + strerror(errno));
    return nullptr;
  }
  return s.release();
}

void tkv_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return;
  if (s->wal_fd >= 0) close(s->wal_fd);
  delete s;
}

void tkv_free(uint8_t* p) { free(p); }

int tkv_apply_batch(void* h, const uint8_t* ops, int64_t len,
                    char* err, int errlen) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string msg;
  if (!do_write(s, ops, static_cast<size_t>(len), &msg)) {
    set_err(err, errlen, msg);
    return -1;
  }
  return 0;
}

int64_t tkv_get(void* h, int col, const uint8_t* k, int64_t kl,
                uint8_t** out) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->cols[col].find(
      std::string(reinterpret_cast<const char*>(k), kl));
  if (it == s->cols[col].end()) return -1;
  *out = copy_out(it->second);
  return static_cast<int64_t>(it->second.size());
}

// Packed result: u32 count | repeated (u32 klen key [u32 vlen val]).
// with_values=0 omits values. reverse=1 returns descending order.
// limit<0 means unbounded.
int64_t tkv_scan(void* h, int col, const uint8_t* start, int64_t sl,
                 const uint8_t* end, int64_t el, int64_t limit,
                 int with_values, int reverse, uint8_t** out) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  Table& t = s->cols[col];
  std::string skey(reinterpret_cast<const char*>(start), sl);
  std::string ekey(reinterpret_cast<const char*>(end), el);
  auto lo = skey.empty() ? t.begin() : t.lower_bound(skey);
  auto hi = ekey.empty() ? t.end() : t.lower_bound(ekey);
  std::string body;
  uint32_t count = 0;
  auto emit = [&](const Table::value_type& kv) {
    put_u32(&body, static_cast<uint32_t>(kv.first.size()));
    body += kv.first;
    if (with_values) {
      put_u32(&body, static_cast<uint32_t>(kv.second.size()));
      body += kv.second;
    }
    ++count;
  };
  if (!reverse) {
    for (auto it = lo; it != hi; ++it) {
      if (limit >= 0 && count >= static_cast<uint64_t>(limit)) break;
      emit(*it);
    }
  } else {
    for (auto it = hi; it != lo;) {
      --it;
      if (limit >= 0 && count >= static_cast<uint64_t>(limit)) break;
      emit(*it);
    }
  }
  std::string packed;
  packed.reserve(4 + body.size());
  put_u32(&packed, count);
  packed += body;
  *out = copy_out(packed);
  return static_cast<int64_t>(packed.size());
}

int64_t tkv_count_range(void* h, int col, const uint8_t* start, int64_t sl,
                        const uint8_t* end, int64_t el) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  Table& t = s->cols[col];
  std::string skey(reinterpret_cast<const char*>(start), sl);
  std::string ekey(reinterpret_cast<const char*>(end), el);
  auto lo = skey.empty() ? t.begin() : t.lower_bound(skey);
  auto hi = ekey.empty() ? t.end() : t.lower_bound(ekey);
  return static_cast<int64_t>(std::distance(lo, hi));
}

int tkv_checkpoint(void* h, char* err, int errlen) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  std::string msg;
  if (!ckpt_write(s, &msg)) {
    set_err(err, errlen, msg);
    return -1;
  }
  return 0;
}

int64_t tkv_wal_bytes(void* h) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return s->wal_bytes;
}

int64_t tkv_count(void* h, int col) {
  auto* s = static_cast<Store*>(h);
  if (!s) return -1;
  if (col < 0 || col >= kNumCols) return -1;
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<int64_t>(s->cols[col].size());
}

}  // extern "C"
