// tpuraft shared multi-group log engine.
//
// Reference parity: RocksDB's role under core:storage/impl/RocksDBLogStorage
// when ONE process hosts MANY raft groups (SURVEY.md §3.1 log-storage row,
// §8.3 "group-sharded column spaces; batched group-fsync"): all groups of a
// process share one engine instance and one write stream, so a flush round
// covering N groups costs ONE fsync (the RocksDB WriteBatch+sync role) and
// the process holds O(total_bytes/seg_max) fds instead of O(groups) segment
// directories.
//
// Layout: a single sequence of journal files shared by every group:
//   journal_<seq>.log : repeated records
//     [u32le len | u32le crc | u32le gid | u8 rectype | payload]
//       len = bytes after the len field; crc = crc32(gid..payload).
//   groups            : atomic registry [u32 gid | u32 nlen | name]*
// Record types:
//   1 entry         payload = LogEntry blob (same format as logstore.cc;
//                   entry-internal CRC retained)
//   2 trunc_suffix  payload = i64 last_kept          (fsynced)
//   3 reset         payload = i64 next_index         (fsynced)
//   4 marker        payload = i64 first, i64 last    (GC state carry)
//   5 trunc_prefix  payload = i64 first_kept         (lazily durable)
//
// Durability contract: tlm_append stages writes (no fsync); tlm_sync
// fsyncs the active journal once for EVERYTHING staged — the Python side
// coalesces concurrent groups' flushes into one tlm_sync (group commit).
// Rotation fsyncs the outgoing file, so only the newest journal can have
// a torn tail; recovery truncates it and (bit-rot only) drops later files.
//
// Index semantics mirror raft: an appended entry with index <= last
// overwrites and truncates the suffix (conflict rule); appends must
// otherwise be contiguous per group.
//
// GC: the oldest journal file is deleted once it holds no live entry
// (live = some group's current position points into it).  Load-bearing
// control records are first re-asserted as a rectype-4 marker in the
// active journal, so dropping the file never loses truncation state.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

constexpr uint8_t kRecEntry = 1;
constexpr uint8_t kRecTruncSuffix = 2;
constexpr uint8_t kRecReset = 3;
constexpr uint8_t kRecMarker = 4;
constexpr uint8_t kRecTruncPrefix = 5;

constexpr uint8_t kEntryMagic = 0xB8;
constexpr uint8_t kTypeConfiguration = 2;
constexpr size_t kEntryHdr = 32;
constexpr size_t kRecHdr = 4 + 4 + 4 + 1;  // len crc gid rectype

uint32_t load_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
int64_t load_i64(const uint8_t* p) { int64_t v; memcpy(&v, p, 8); return v; }

bool fsync_fd(int fd) { return ::fsync(fd) == 0; }

bool fsync_dir(const std::string& dir) {
  int fd = ::open(dir.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = fsync_fd(fd);
  ::close(fd);
  return ok;
}

bool write_all(int fd, const uint8_t* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::write(fd, buf, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += n;
    len -= (size_t)n;
  }
  return true;
}

struct Loc {
  uint32_t file;  // journal seq
  uint32_t off;   // record offset within the file (points at len field)
};

struct GroupLog {
  std::string name;
  uint64_t reg_epoch_at = 0;   // registry epoch of this group's record
  int64_t first = 1;
  int64_t base = 1;            // index of positions.front()
  std::deque<Loc> positions;   // base .. base+size-1
  std::vector<int64_t> conf_indexes;

  int64_t last() const { return base + (int64_t)positions.size() - 1; }
  bool has(int64_t idx) const { return idx >= base && idx <= last(); }
};

struct JournalFile {
  uint32_t seq = 0;
  int fd = -1;
  int64_t size = 0;
  int64_t live_entries = 0;       // positions currently pointing here
  std::set<uint32_t> groups;      // gids with ANY record in this file

  std::string path(const std::string& dir) const {
    char buf[32];
    snprintf(buf, sizeof(buf), "journal_%08u.log", seq);
    return dir + "/" + buf;
  }
};

struct tlm_handle {
  std::string dir;
  int64_t seg_max = 64LL << 20;
  std::mutex mu;
  std::mutex sync_mu;            // serializes fsync rounds (NOT under mu)
  uint64_t write_epoch = 0;      // bumped per staged write (under mu)
  uint64_t synced_epoch = 0;     // last epoch covered by an fsync
  std::map<uint32_t, GroupLog> groups;
  std::map<std::string, uint32_t> by_name;
  uint32_t next_gid = 1;
  std::deque<std::unique_ptr<JournalFile>> files;  // oldest..newest
  int64_t sync_rounds = 0;       // fsync calls through tlm_sync
  int64_t appends = 0;           // tlm_append calls (coalescing ratio)
  bool active_dirty = false;     // staged bytes not yet fsynced
  int reg_fd = -1;               // append-only group registry
  // registry epochs mirror write_epoch/synced_epoch: a bool flag would
  // lose a registration racing a sync round's post-fsync clear
  uint64_t reg_epoch = 0;        // bumped per registry append (under mu)
  uint64_t reg_synced_epoch = 0; // last registry epoch fsynced

  JournalFile* file_by_seq(uint32_t seq) {
    for (auto& f : files)
      if (f->seq == seq) return f.get();
    return nullptr;
  }

  JournalFile* active() { return files.empty() ? nullptr : files.back().get(); }

  // The registry is APPEND-ONLY ([u32 gid | u32 name_len | name] per
  // group): rewriting the whole file per registration made booting G
  // groups O(G^2) bytes + G rename+fsync rounds (profiled: 1.7ms per
  // registration at 1K, the dominant 16K-boot cost).  Registration
  // appends one record (no fsync); the NEXT sync round fsyncs the
  // registry BEFORE the journal, so a journal record's gid can never
  // be durable without its registry entry.
  bool append_group_record(uint32_t gid, const std::string& name) {
    if (reg_fd < 0) return false;
    std::string buf;
    uint32_t nl = (uint32_t)name.size();
    buf.append((const char*)&gid, 4);
    buf.append((const char*)&nl, 4);
    buf += name;
    // a partial write mid-file would make every LATER record misparse
    // at boot: retry shorts (write_all), and roll a failed append back
    // to the pre-write offset so the stream stays clean
    off_t at = ::lseek(reg_fd, 0, SEEK_CUR);
    if (!write_all(reg_fd, (const uint8_t*)buf.data(), buf.size())) {
      if (at >= 0) {
        (void)!::ftruncate(reg_fd, at);
        ::lseek(reg_fd, at, SEEK_SET);
      }
      return false;
    }
    ++reg_epoch;
    return true;
  }

  // fsync the registry if it has unsynced appends; call BEFORE any
  // journal fsync — a journal record's gid must never be durable
  // without its registry entry (an orphan gid would shadow the group's
  // data after a re-register).  Safe under mu (locked control-record
  // paths) and from sync_unlocked's pre-snapshot.
  bool flush_registry_locked(std::string* err) {
    if (reg_epoch <= reg_synced_epoch || reg_fd < 0) return true;
    uint64_t target = reg_epoch;
    if (!fsync_fd(reg_fd)) { *err = "registry fsync failed"; return false; }
    if (reg_synced_epoch < target) reg_synced_epoch = target;
    return true;
  }

  // Returns false when the registry cannot be READ (open failure, or a
  // short/failed read of an existing file).  The caller must treat that
  // as fatal for the whole open: the journal scan's unregistered-gid
  // guard depends on a complete registry — scanning with a partial one
  // would misread every group's acked records as orphan garbage and
  // truncate the journals to nothing.
  bool load_groups() {
    reg_fd = ::open((dir + "/groups").c_str(),
                    O_RDWR | O_CREAT | O_CLOEXEC, 0644);
    if (reg_fd < 0) return false;
    fsync_dir(dir);  // the one-time file creation
    struct stat st {};
    size_t good = 0;
    bool read_ok = false;
    // fstat failure must NOT read as "fresh empty registry" (st is
    // zero-initialized): an empty groups map + populated journals would
    // send every record into the unregistered-gid tear below.  The
    // caller fails the open and closes reg_fd.
    if (::fstat(reg_fd, &st) != 0) return false;
    if (st.st_size > 0) {
      std::vector<uint8_t> buf((size_t)st.st_size);
      size_t got = 0;
      while (got < buf.size()) {
        ssize_t n = ::pread(reg_fd, buf.data() + got, buf.size() - got,
                            (off_t)got);
        if (n < 0 && errno == EINTR) continue;
        if (n <= 0) break;
        got += (size_t)n;
      }
      if (got == buf.size()) {
        read_ok = true;
        size_t off = 0;
        uint32_t expect = 1;
        while (off + 8 <= buf.size()) {
          uint32_t gid = load_u32(buf.data() + off);
          uint32_t nl = load_u32(buf.data() + off + 4);
          if (off + 8 + nl > buf.size()) break;  // torn append
          // Registry records carry no per-record CRC, but gids are
          // allocated monotonically under mu, so records MUST carry
          // strictly increasing gids.  A violation is unsynced-tail
          // garbage (partial-page writeback can flip bits there):
          // without this check a flipped gid byte could ALIAS an acked
          // gid and shadow that group's log.  Treat it as a torn tail.
          // Strictly INCREASING — not gap-free — because registries
          // written before register_group rolled next_gid back on a
          // failed append can legally hold gaps in their durable
          // region; demanding exact sequence would truncate those
          // acked registrations on upgrade.  (A flipped NAME byte in
          // the tail stays undetected — it only garbles an unacked
          // group's name, never aliases a gid; a flipped-HIGH gid
          // registers a garbage gid whose real records then hit the
          // journal scan's unregistered-gid tear.)  Known residual:
          // records carry no per-record CRC, so rot in the FSYNCED
          // region is indistinguishable from tail garbage and gets
          // truncated rather than failing loudly — strictly safer than
          // the silent gid aliasing the unguarded parse allowed, but a
          // future registry format bump should add per-record CRCs.
          if (gid < expect) break;
          expect = gid + 1;
          off += 8;
          std::string name((const char*)buf.data() + off, nl);
          off += nl;
          groups[gid].name = name;
          by_name[name] = gid;
          next_gid = std::max(next_gid, gid + 1);
          good = off;
        }
      }
    } else if (st.st_size == 0) {
      read_ok = true;  // fresh registry
    }
    // drop a torn TAIL so later appends extend a clean record stream —
    // but only after a successful full read: truncating on a failed
    // read would forget every group (journal gids would orphan)
    if (read_ok && good < (size_t)st.st_size)
      (void)!::ftruncate(reg_fd, (off_t)good);
    ::lseek(reg_fd, (off_t)(read_ok ? good : st.st_size), SEEK_SET);
    return read_ok;
  }

  // -- record application (shared by recovery scan and live appends) --------

  void drop_loc_count(const Loc& l) {
    JournalFile* f = file_by_seq(l.file);
    if (f) --f->live_entries;
  }

  void clamp_suffix(GroupLog& g, int64_t last_kept) {
    while (g.last() > last_kept && !g.positions.empty()) {
      drop_loc_count(g.positions.back());
      g.positions.pop_back();
    }
    while (!g.conf_indexes.empty() && g.conf_indexes.back() > last_kept)
      g.conf_indexes.pop_back();
  }

  void clamp_prefix(GroupLog& g, int64_t first_kept) {
    if (first_kept <= g.first) return;
    g.first = first_kept;
    while (!g.positions.empty() && g.base < first_kept) {
      drop_loc_count(g.positions.front());
      g.positions.pop_front();
      ++g.base;
    }
    if (g.positions.empty()) g.base = std::max(g.base, first_kept);
    size_t keep = 0;
    while (keep < g.conf_indexes.size() && g.conf_indexes[keep] < first_kept)
      ++keep;
    if (keep)
      g.conf_indexes.erase(g.conf_indexes.begin(),
                           g.conf_indexes.begin() + (long)keep);
  }

  void reset_group(GroupLog& g, int64_t next_index) {
    for (const Loc& l : g.positions) drop_loc_count(l);
    g.positions.clear();
    g.conf_indexes.clear();
    g.first = next_index;
    g.base = next_index;
  }

  // Returns false only for structurally invalid ENTRY sequencing (live
  // append validation); the recovery scan treats false as corruption.
  bool apply_record(uint32_t gid, uint8_t rectype, const uint8_t* payload,
                    size_t plen, Loc loc, std::string* err) {
    GroupLog& g = groups[gid];  // callers verified gid is registered
    switch (rectype) {
      case kRecEntry: {
        if (plen < kEntryHdr || payload[0] != kEntryMagic) {
          *err = "bad entry blob";
          return false;
        }
        int64_t idx = load_i64(payload + 12);
        if (g.positions.empty()) {
          // first entry after open/reset/suffix-trunc-to-empty
          if (idx < g.first) {
            *err = "append below first_log_index";
            return false;
          }
          g.base = idx;
        } else if (idx <= g.last()) {
          clamp_suffix(g, idx - 1);  // conflict overwrite truncates
          if (g.positions.empty()) g.base = idx;
        } else if (idx != g.last() + 1) {
          *err = "non-contiguous append: have last=" +
                 std::to_string(g.last()) + ", got " + std::to_string(idx);
          return false;
        }
        g.positions.push_back(loc);
        JournalFile* f = file_by_seq(loc.file);
        if (f) ++f->live_entries;
        if (payload[1] == kTypeConfiguration) g.conf_indexes.push_back(idx);
        return true;
      }
      case kRecTruncSuffix:
        if (plen < 8) { *err = "short trunc record"; return false; }
        clamp_suffix(g, load_i64(payload));
        return true;
      case kRecReset:
        if (plen < 8) { *err = "short reset record"; return false; }
        reset_group(g, load_i64(payload));
        return true;
      case kRecMarker: {
        if (plen < 16) { *err = "short marker"; return false; }
        int64_t mf = load_i64(payload), ml = load_i64(payload + 8);
        clamp_suffix(g, ml);
        clamp_prefix(g, mf);
        return true;
      }
      case kRecTruncPrefix:
        if (plen < 8) { *err = "short trunc record"; return false; }
        clamp_prefix(g, load_i64(payload));
        return true;
      default:
        *err = "unknown record type";
        return false;
    }
  }

  // -- writing ---------------------------------------------------------------

  bool rotate_locked(std::string* err) {
    if (active() != nullptr) {
      // outgoing file becomes immutable: make it durable NOW so only
      // the newest journal can ever have a torn tail
      if (!fsync_fd(active()->fd)) { *err = "rotate fsync failed"; return false; }
    }
    auto f = std::make_unique<JournalFile>();
    f->seq = files.empty() ? 1 : files.back()->seq + 1;
    f->fd = ::open(f->path(dir).c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
    if (f->fd < 0) { *err = std::string("journal create: ") + strerror(errno); return false; }
    files.push_back(std::move(f));
    if (!fsync_dir(dir)) { *err = "dir fsync failed"; return false; }
    return true;
  }

  bool write_record_locked(uint32_t gid, uint8_t rectype,
                           const uint8_t* payload, size_t plen,
                           Loc* loc_out, std::string* err) {
    // staging invariant: no journal byte for a gid may exist before
    // its registry entry is DURABLE — any concurrent round's journal
    // fsync covers all staged bytes, so ordering fsyncs inside rounds
    // cannot close this on its own.  One registry fsync per group's
    // first record at most (usually a prior round already covered it).
    auto git = groups.find(gid);
    if (git != groups.end()
        && git->second.reg_epoch_at > reg_synced_epoch) {
      if (!flush_registry_locked(err)) return false;
    }
    if (active() == nullptr || active()->size >= seg_max) {
      if (!rotate_locked(err)) return false;
    }
    JournalFile* f = active();
    std::vector<uint8_t> rec(kRecHdr + plen);
    uint32_t len = (uint32_t)(4 + 4 + 1 + plen);
    memcpy(rec.data(), &len, 4);
    memcpy(rec.data() + 8, &gid, 4);
    rec[12] = rectype;
    memcpy(rec.data() + 13, payload, plen);
    uLong c = crc32(0L, Z_NULL, 0);
    c = crc32(c, rec.data() + 8, (uInt)(4 + 1 + plen));
    uint32_t crc = (uint32_t)c;
    memcpy(rec.data() + 4, &crc, 4);
    if (!write_all(f->fd, rec.data(), rec.size())) {
      *err = std::string("journal write: ") + strerror(errno);
      return false;
    }
    if (loc_out) *loc_out = Loc{f->seq, (uint32_t)f->size};
    f->size += (int64_t)rec.size();
    f->groups.insert(gid);
    active_dirty = true;
    ++write_epoch;
    return true;
  }

  bool write_control_locked(uint32_t gid, uint8_t rectype, int64_t a,
                            std::string* err, int64_t b = INT64_MIN) {
    uint8_t payload[16];
    memcpy(payload, &a, 8);
    size_t plen = 8;
    if (b != INT64_MIN) {
      memcpy(payload + 8, &b, 8);
      plen = 16;
    }
    return write_record_locked(gid, rectype, payload, plen, nullptr, err);
  }

  bool sync_active_locked(std::string* err) {
    if (!flush_registry_locked(err)) return false;  // registry FIRST
    if (active() == nullptr || !active_dirty) return true;
    if (!fsync_fd(active()->fd)) { *err = "fsync failed"; return false; }
    active_dirty = false;
    synced_epoch = write_epoch;
    ++sync_rounds;
    return true;
  }

  // The group-commit sync: fsync OUTSIDE mu, so concurrent staging
  // (which runs inline on the host event loop) never blocks behind a
  // flush round.  sync_mu serializes rounds; the epoch check lets a
  // caller whose bytes another thread's round already covered return
  // without a redundant fsync.
  bool sync_unlocked(std::string* err) {
    std::lock_guard<std::mutex> sg(sync_mu);
    int fd = -1, rfd = -1;
    uint64_t target, rtarget;
    {
      std::lock_guard<std::mutex> g(mu);
      target = write_epoch;
      rtarget = reg_epoch;
      if (rtarget > reg_synced_epoch) rfd = reg_fd;
      if ((synced_epoch >= target || active() == nullptr) && rfd < 0)
        return true;
      // only touch the journal when IT has unsynced bytes — a
      // registry-only round must not pay a redundant journal fsync
      if (synced_epoch < target && active() != nullptr)
        fd = active()->fd;
    }
    // registry FIRST: a journal record's gid must never be durable
    // without its registry entry (an orphan gid would shadow the
    // group's data after a re-register).  The epoch snapshot bounds
    // what this fsync proves: a registration racing this round keeps
    // reg_epoch > reg_synced_epoch and the next round covers it.
    if (rfd >= 0) {
      if (!fsync_fd(rfd)) { *err = "registry fsync failed"; return false; }
      std::lock_guard<std::mutex> g(mu);
      if (reg_synced_epoch < rtarget) reg_synced_epoch = rtarget;
    }
    if (fd >= 0) {
      if (!fsync_fd(fd)) { *err = "fsync failed"; return false; }
    }
    {
      std::lock_guard<std::mutex> g(mu);
      if (synced_epoch < target) synced_epoch = target;
      if (write_epoch == target) active_dirty = false;
      ++sync_rounds;
    }
    return true;
  }
};

}  // namespace

extern "C" {

tlm_handle* tlm_open(const char* dir_path, int64_t seg_max_bytes,
                     char* errbuf, int errlen) {
  auto set_err = [&](const std::string& msg) {
    if (errbuf && errlen > 0) snprintf(errbuf, (size_t)errlen, "%s", msg.c_str());
  };
  auto h = std::make_unique<tlm_handle>();
  h->dir = dir_path;
  if (seg_max_bytes > 0) h->seg_max = seg_max_bytes;
  // every error return below must release what was opened so far:
  // open failures are RETRYABLE (transient EIO), and a caller looping
  // on retries must not leak fds per attempt until EMFILE
  auto fail_close = [&]() {
    for (auto& f : h->files)
      if (f->fd >= 0) ::close(f->fd);
    h->files.clear();
    if (h->reg_fd >= 0) {
      ::close(h->reg_fd);
      h->reg_fd = -1;
    }
  };
  if (::mkdir(dir_path, 0755) != 0 && errno != EEXIST) {
    set_err(std::string("mkdir failed: ") + strerror(errno));
    return nullptr;
  }
  if (!h->load_groups()) {
    // FAIL the open rather than scan with a partial registry: the
    // unregistered-gid guard below would read every group's acked
    // records as orphan garbage and truncate the journals to nothing —
    // a transient registry EIO must surface as a retryable open error,
    // never as data destruction.
    fail_close();
    set_err("groups registry unreadable");
    return nullptr;
  }

  std::vector<std::pair<uint32_t, std::string>> names;
  DIR* d = ::opendir(dir_path);
  if (!d) {
    set_err(std::string("opendir failed: ") + strerror(errno));
    fail_close();
    return nullptr;
  }
  while (struct dirent* ent = ::readdir(d)) {
    std::string n = ent->d_name;
    if (n.rfind("journal_", 0) == 0 && n.size() == 20 &&
        n.compare(n.size() - 4, 4, ".log") == 0) {
      names.emplace_back((uint32_t)strtoul(n.c_str() + 8, nullptr, 10), n);
    }
  }
  ::closedir(d);
  std::sort(names.begin(), names.end());

  bool drop_rest = false;
  for (auto& [seq, name] : names) {
    std::string path = h->dir + "/" + name;
    if (drop_rest) {
      ::unlink(path.c_str());
      continue;
    }
    auto f = std::make_unique<JournalFile>();
    f->seq = seq;
    f->fd = ::open(path.c_str(), O_RDWR | O_APPEND, 0644);
    if (f->fd < 0) continue;
    struct stat st;
    if (::fstat(f->fd, &st) != 0) {
      set_err("fstat failed");
      ::close(f->fd);  // not yet in h->files: fail_close won't see it
      fail_close();
      return nullptr;
    }
    std::vector<uint8_t> buf((size_t)st.st_size);
    if (st.st_size > 0 &&
        ::pread(f->fd, buf.data(), buf.size(), 0) != (ssize_t)buf.size()) {
      set_err("journal read failed");
      ::close(f->fd);
      fail_close();
      return nullptr;
    }
    // the file must be registered before records apply (live counts)
    JournalFile* fp = f.get();
    h->files.push_back(std::move(f));
    int64_t off = 0, good_end = 0;
    while (off + (int64_t)kRecHdr <= st.st_size) {
      uint32_t len = load_u32(buf.data() + off);
      if (len < 9 || off + 4 + (int64_t)len > st.st_size) break;  // torn
      uint32_t crc = load_u32(buf.data() + off + 4);
      uLong c = crc32(0L, Z_NULL, 0);
      c = crc32(c, buf.data() + off + 8, (uInt)(len - 4));
      if ((uint32_t)c != crc) break;  // torn/corrupt
      uint32_t gid = load_u32(buf.data() + off + 8);
      uint8_t rectype = buf[(size_t)off + 12];
      // Power-loss orphan guard: a record whose gid has no registry
      // entry can only be an unsynced tail — every sync round fsyncs
      // the registry BEFORE the journal, so any DURABLY ACKED journal
      // byte at or past this offset would imply the registry entry is
      // durable too.  Adopting the record instead would let a future
      // re-register reassign the gid and shadow this data (and a
      // contiguity clash between orphan and adopted entries could tear
      // the scan mid-journal, dropping later groups' acked records).
      if (h->groups.find(gid) == h->groups.end())
        break;  // unregistered gid -> unacked tail: truncate here
      std::string aerr;
      if (!h->apply_record(gid, rectype, buf.data() + off + 13, len - 9,
                           Loc{seq, (uint32_t)off}, &aerr))
        break;  // structurally bad -> treat as tear
      off += 4 + (int64_t)len;
      good_end = off;
    }
    if (good_end < st.st_size) {
      // torn tail: truncate; everything after (later files) is
      // unreachable (they were created after this tail was written)
      if (::ftruncate(fp->fd, good_end) != 0) {
        set_err("torn-tail truncate failed");
        fail_close();
        return nullptr;
      }
      drop_rest = true;
    }
    fp->size = good_end;
  }
  return h.release();
}

void tlm_close(tlm_handle* h) {
  if (!h) return;
  {
    std::lock_guard<std::mutex> g(h->mu);
    for (auto& f : h->files)
      if (f->fd >= 0) ::close(f->fd);
    h->files.clear();
    if (h->reg_fd >= 0) {
      if (h->reg_epoch > h->reg_synced_epoch) (void)fsync_fd(h->reg_fd);
      ::close(h->reg_fd);
      h->reg_fd = -1;
    }
  }
  delete h;
}

// Registers (or looks up) a group by name; returns its gid, or 0 on error.
uint32_t tlm_register_group(tlm_handle* h, const char* name,
                            char* errbuf, int errlen) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->by_name.find(name);
  if (it != h->by_name.end()) return it->second;
  uint32_t gid = h->next_gid++;
  h->groups[gid].name = name;
  h->by_name[name] = gid;
  h->groups[gid].reg_epoch_at = h->reg_epoch + 1;  // set by the append
  if (!h->append_group_record(gid, name)) {
    // roll the registration back COMPLETELY: leaving the gid cached in
    // by_name would make a retried register return it without any
    // registry record staged (the staging guard in write_record_locked
    // then passes vacuously), so journal records could become durable
    // for a gid absent from the registry — on reboot the gid orphans
    // and next_gid could reassign it, shadowing the group's data.
    h->groups.erase(gid);
    h->by_name.erase(name);
    h->next_gid = gid;  // we hold mu: nobody consumed a later gid
    if (errbuf && errlen > 0)
      snprintf(errbuf, (size_t)errlen, "groups registry write failed");
    return 0;
  }
  return gid;
}

int64_t tlm_first(tlm_handle* h, uint32_t gid) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  return it == h->groups.end() ? 1 : it->second.first;
}

int64_t tlm_last(tlm_handle* h, uint32_t gid) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  if (it == h->groups.end()) return 0;
  GroupLog& gl = it->second;
  return gl.positions.empty() ? gl.first - 1 : gl.last();
}

// frames = concatenated [u32le blob_len | entry blob] (the LogStorage batch
// format).  Stages the records; durability comes from tlm_sync.  Live
// appends must be strictly contiguous per group (LogManager truncates
// explicitly first); the overwrite rule only serves the recovery scan.
int64_t tlm_append(tlm_handle* h, uint32_t gid, const uint8_t* frames,
                   int64_t total, char* errbuf, int errlen) {
  auto fail = [&](const std::string& msg) -> int64_t {
    if (errbuf && errlen > 0) snprintf(errbuf, (size_t)errlen, "%s", msg.c_str());
    return -1;
  };
  std::lock_guard<std::mutex> g(h->mu);
  auto git = h->groups.find(gid);
  if (git == h->groups.end()) return fail("unregistered group");
  GroupLog& gl = git->second;

  // Pass 1: validate frames + contiguity up front.
  struct Pending {
    const uint8_t* blob;
    uint32_t blen;
  };
  std::vector<Pending> pend;
  int64_t expected = gl.positions.empty() ? -1 : gl.last() + 1;
  int64_t off = 0;
  while (off < total) {
    if (off + 4 > total) return fail("truncated frame header");
    uint32_t blen = load_u32(frames + off);
    if (off + 4 + (int64_t)blen > total) return fail("truncated frame");
    const uint8_t* blob = frames + off + 4;
    if (blen < kEntryHdr || blob[0] != kEntryMagic)
      return fail("bad entry blob");
    int64_t idx = load_i64(blob + 12);
    if (expected == -1) {
      if (idx < gl.first) return fail("append below first_log_index");
    } else if (idx != expected) {
      return fail("non-contiguous append: have last=" +
                  std::to_string(expected - 1) + ", got " +
                  std::to_string(idx));
    }
    expected = idx + 1;
    pend.push_back({blob, blen});
    off += 4 + (int64_t)blen;
  }
  if (pend.empty()) return 0;

  // Pass 2: write in segment-sized runs — ONE write() per touched
  // journal — then index.  Index updates happen only after the run's
  // bytes hit the fd, so a failed write leaves the in-memory index
  // consistent with the durable prefix.
  std::string err;
  size_t i = 0;
  while (i < pend.size()) {
    if (h->active() == nullptr || h->active()->size >= h->seg_max) {
      if (!h->rotate_locked(&err)) return fail(err);
    }
    JournalFile* f = h->active();
    std::string buf;
    std::vector<std::pair<Loc, size_t>> staged;  // (loc, pend idx)
    int64_t fsize = f->size;
    size_t j = i;
    while (j < pend.size() && (staged.empty() || fsize < h->seg_max)) {
      const Pending& p = pend[j];
      uint32_t len = (uint32_t)(4 + 4 + 1 + p.blen);
      size_t base = buf.size();
      buf.resize(base + 4 + len);
      uint8_t* rec = (uint8_t*)buf.data() + base;
      memcpy(rec, &len, 4);
      memcpy(rec + 8, &gid, 4);
      rec[12] = kRecEntry;
      memcpy(rec + 13, p.blob, p.blen);
      uLong c = crc32(0L, Z_NULL, 0);
      c = crc32(c, rec + 8, (uInt)(4 + 1 + p.blen));
      uint32_t crc = (uint32_t)c;
      memcpy(rec + 4, &crc, 4);
      staged.emplace_back(Loc{f->seq, (uint32_t)fsize}, j);
      fsize += (int64_t)(4 + len);
      ++j;
    }
    if (!write_all(f->fd, (const uint8_t*)buf.data(), buf.size()))
      return fail(std::string("journal write: ") + strerror(errno));
    f->size = fsize;
    f->groups.insert(gid);
    h->active_dirty = true;
    for (auto& [loc, pi] : staged) {
      if (!h->apply_record(gid, kRecEntry, pend[pi].blob, pend[pi].blen,
                           loc, &err))
        return fail(err);  // unreachable after pass-1 validation
    }
    i = j;
  }
  ++h->appends;
  return (int64_t)pend.size();
}

// ONE fsync covering every group's staged appends since the last sync.
// The fsync runs OUTSIDE the engine mutex (see sync_unlocked).
int tlm_sync(tlm_handle* h, char* errbuf, int errlen) {
  std::string err;
  if (!h->sync_unlocked(&err)) {
    if (errbuf && errlen > 0) snprintf(errbuf, (size_t)errlen, "%s", err.c_str());
    return -1;
  }
  return 0;
}

int64_t tlm_sync_count(tlm_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return h->sync_rounds;
}

int64_t tlm_append_count(tlm_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return h->appends;
}

int64_t tlm_file_count(tlm_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return (int64_t)h->files.size();
}

// Returns blob length and sets *out (caller frees with tlm_free); -1 on
// a missing index, -2 on record corruption (CRC/gid mismatch — bit rot
// of a record the index says is live; callers must fail LOUDLY, not
// treat it as a hole).  The preads run OUTSIDE the engine mutex (a cold
// read must not stall every group's appends); the fd is dup'd under the
// lock so a racing GC unlink/close cannot invalidate it mid-read.
int64_t tlm_get(tlm_handle* h, uint32_t gid, int64_t index, uint8_t** out) {
  int fd = -1;
  Loc loc{0, 0};
  {
    std::lock_guard<std::mutex> g(h->mu);
    auto it = h->groups.find(gid);
    if (it == h->groups.end()) return -1;
    GroupLog& gl = it->second;
    if (index < gl.first || !gl.has(index)) return -1;
    loc = gl.positions[(size_t)(index - gl.base)];
    JournalFile* f = h->file_by_seq(loc.file);
    if (!f) return -1;
    fd = ::dup(f->fd);
    if (fd < 0) return -1;
  }
  // -1 = environmental failure (short pread, malloc) — indistinct from
  // a missing record, NOT a corruption verdict; -2 only when the bytes
  // were fully read and the CRC or stored gid actually mismatches.
  int64_t result = -1;
  uint8_t hdr[kRecHdr];
  struct stat st {};
  if (::pread(fd, hdr, kRecHdr, loc.off) == (ssize_t)kRecHdr &&
      ::fstat(fd, &st) == 0) {
    uint32_t len = load_u32(hdr);
    // CRC-guard the read path, not just recovery: the stored crc covers
    // gid..payload, so recompute over the header tail + blob and reject
    // rotted records instead of silently decoding garbage.  A len rotted
    // HIGH overruns the journal extent (records are never physically
    // truncated under a live index — suffix truncation only clamps the
    // in-memory positions, GC unlinks whole files) — that is corruption
    // too, not a short read to shrug off as a hole.
    if (len < 9 || load_u32(hdr + 8) != gid ||
        loc.off + 4 + (int64_t)len > (int64_t)st.st_size) {
      result = -2;  // framing/gid contradicts the live index: corruption
    } else {
      uint32_t blen = len - 9;
      uint8_t* blob = (uint8_t*)malloc(blen ? blen : 1);
      if (blob) {
        if (::pread(fd, blob, blen, loc.off + kRecHdr) == (ssize_t)blen) {
          uLong c = crc32(0L, Z_NULL, 0);
          c = crc32(c, hdr + 8, 5);          // gid + rectype
          c = crc32(c, blob, (uInt)blen);     // payload
          if ((uint32_t)c == load_u32(hdr + 4)) {
            *out = blob;
            result = (int64_t)blen;
          } else {
            free(blob);
            result = -2;
          }
        } else {
          free(blob);
        }
      }
    }
  }
  ::close(fd);
  return result;
}

void tlm_free(uint8_t* buf) { free(buf); }

int tlm_truncate_prefix(tlm_handle* h, uint32_t gid, int64_t first_kept) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  if (it == h->groups.end()) return -1;
  if (first_kept <= it->second.first) return 0;
  std::string err;
  // lazily durable: losing this record only means re-keeping entries
  if (!h->write_control_locked(gid, kRecTruncPrefix, first_kept, &err))
    return -1;
  h->clamp_prefix(it->second, first_kept);
  return 0;
}

int tlm_truncate_suffix(tlm_handle* h, uint32_t gid, int64_t last_kept) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  if (it == h->groups.end()) return -1;
  GroupLog& gl = it->second;
  if (gl.positions.empty() || gl.last() <= last_kept) return 0;
  std::string err;
  // durability-critical (raft conflict resolution): record + fsync
  if (!h->write_control_locked(gid, kRecTruncSuffix, last_kept, &err))
    return -1;
  if (!h->sync_active_locked(&err)) return -1;
  h->clamp_suffix(gl, last_kept);
  return 0;
}

int tlm_reset(tlm_handle* h, uint32_t gid, int64_t next_index) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  if (it == h->groups.end()) return -1;
  std::string err;
  if (!h->write_control_locked(gid, kRecReset, next_index, &err)) return -1;
  if (!h->sync_active_locked(&err)) return -1;
  h->reset_group(it->second, next_index);
  return 0;
}

int64_t tlm_conf_count(tlm_handle* h, uint32_t gid) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  return it == h->groups.end() ? 0 : (int64_t)it->second.conf_indexes.size();
}

int64_t tlm_conf_indexes(tlm_handle* h, uint32_t gid, int64_t* out,
                         int64_t cap) {
  std::lock_guard<std::mutex> g(h->mu);
  auto it = h->groups.find(gid);
  if (it == h->groups.end()) return 0;
  auto& v = it->second.conf_indexes;
  int64_t n = std::min<int64_t>(cap, (int64_t)v.size());
  for (int64_t i = 0; i < n; ++i) out[i] = v[(size_t)i];
  return n;
}

// Deletes fully-dead oldest journal files.  Returns files deleted, -1 on
// error.  Never touches the active (newest) file.
int64_t tlm_gc(tlm_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  int64_t deleted = 0;
  std::string err;
  while (h->files.size() > 1) {
    JournalFile* f = h->files.front().get();
    if (f->live_entries > 0) break;
    // re-assert every resident group's state as a marker in the active
    // journal, so dropping this file's control records loses nothing
    for (uint32_t gid : f->groups) {
      auto it = h->groups.find(gid);
      if (it == h->groups.end()) continue;
      GroupLog& gl = it->second;
      int64_t last = gl.positions.empty() ? gl.first - 1 : gl.last();
      if (!h->write_control_locked(gid, kRecMarker, gl.first, &err, last))
        return -1;
    }
    if (!h->sync_active_locked(&err)) return -1;
    std::string path = f->path(h->dir);
    ::close(f->fd);
    ::unlink(path.c_str());
    h->files.pop_front();
    if (!fsync_dir(h->dir)) return -1;
    ++deleted;
  }
  return deleted;
}

}  // extern "C"
