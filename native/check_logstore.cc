// Sanitizer exercise driver for the log storage engine (logstore.cc).
//
// Built with -fsanitize=thread / address by `make -C native san` and run
// by `make check` (SURVEY.md §6 "race detection": the reference leans on
// JVM memory safety + lock discipline; the C++ engines get TSAN/ASAN
// builds in CI instead).  Drives the real C ABI concurrently:
// one appender (raft log appends are single-writer by design) against
// readers and a prefix-truncator, then reopen-and-verify.

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>
#include <zlib.h>

extern "C" {
struct tls_handle;
tls_handle* tls_open(const char* dir, int64_t seg_max, char* err, int errlen);
void tls_close(tls_handle* h);
int64_t tls_first_index(tls_handle* h);
int64_t tls_last_index(tls_handle* h);
int64_t tls_get(tls_handle* h, int64_t index, uint8_t** out);
void tls_free(uint8_t* buf);
int64_t tls_append(tls_handle* h, const uint8_t* frames, int64_t total,
                   int sync, char* err, int errlen);
int tls_truncate_prefix(tls_handle* h, int64_t first_kept);
int tls_truncate_suffix(tls_handle* h, int64_t last_kept);
}

namespace {

constexpr size_t kHdr = 32;

// Entry blob per tpuraft/entity.py _HDR "<BBHqqHHII".
std::string make_frame(int64_t index, int64_t term, const std::string& data) {
  std::string blob(kHdr, '\0');
  uint8_t* p = reinterpret_cast<uint8_t*>(blob.data());
  p[0] = 0xB8;
  p[1] = 1;  // DATA
  memcpy(p + 4, &term, 8);
  memcpy(p + 12, &index, 8);
  uint32_t dl = static_cast<uint32_t>(data.size());
  memcpy(p + 24, &dl, 4);
  uLong c = crc32(0L, Z_NULL, 0);
  c = crc32(c, reinterpret_cast<const Bytef*>(data.data()), dl);
  uint32_t crc = static_cast<uint32_t>(c);
  memcpy(p + 28, &crc, 4);
  blob += data;
  uint32_t flen = static_cast<uint32_t>(blob.size());
  std::string frame(4, '\0');
  memcpy(frame.data(), &flen, 4);
  return frame + blob;
}

}  // namespace

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp/tpuraft_check_logstore";
  std::string cmd = std::string("rm -rf ") + dir;
  if (system(cmd.c_str()) != 0) return 2;
  char err[256] = {0};
  tls_handle* h = tls_open(dir, 1 << 16 /*small segs -> many rotations*/,
                           err, sizeof(err));
  if (!h) {
    fprintf(stderr, "open failed: %s\n", err);
    return 1;
  }

  constexpr int64_t kN = 4000;
  std::atomic<int64_t> appended{0};
  std::atomic<bool> stop{false};

  std::thread appender([&] {
    for (int64_t i = 1; i <= kN; ++i) {
      std::string f = make_frame(i, 7, "payload-" + std::to_string(i));
      char e[256];
      int64_t n = tls_append(h, reinterpret_cast<const uint8_t*>(f.data()),
                             static_cast<int64_t>(f.size()),
                             (i % 64) == 0 /*periodic fsync*/, e, sizeof(e));
      if (n != 1) {
        fprintf(stderr, "append %lld failed: %s\n", (long long)i, e);
        abort();
      }
      appended.store(i, std::memory_order_release);
    }
  });

  std::vector<std::thread> readers;
  for (int t = 0; t < 3; ++t) {
    readers.emplace_back([&] {
      uint64_t checked = 0;
      while (!stop.load(std::memory_order_acquire)) {
        int64_t hi = appended.load(std::memory_order_acquire);
        int64_t lo = tls_first_index(h);
        if (hi < lo) continue;
        int64_t idx = lo + (checked * 97) % (hi - lo + 1);
        uint8_t* blob = nullptr;
        int64_t n = tls_get(h, idx, &blob);
        if (n > 0) {
          int64_t got;
          memcpy(&got, blob + 12, 8);
          if (got != idx) {
            fprintf(stderr, "index mismatch %lld != %lld\n", (long long)got,
                    (long long)idx);
            abort();
          }
          tls_free(blob);
        }
        ++checked;
      }
    });
  }

  std::thread truncator([&] {
    while (!stop.load(std::memory_order_acquire)) {
      int64_t hi = appended.load(std::memory_order_acquire);
      if (hi > 600) {
        tls_truncate_prefix(h, hi - 500);
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
  });

  appender.join();
  stop.store(true, std::memory_order_release);
  for (auto& r : readers) r.join();
  truncator.join();

  if (tls_last_index(h) != kN) {
    fprintf(stderr, "last index %lld != %lld\n",
            (long long)tls_last_index(h), (long long)kN);
    return 1;
  }
  // suffix truncation + reopen survives
  if (tls_truncate_suffix(h, kN - 10) != 0) return 1;
  tls_close(h);
  h = tls_open(dir, 1 << 16, err, sizeof(err));
  if (!h || tls_last_index(h) != kN - 10) {
    fprintf(stderr, "reopen: %s last=%lld\n", err,
            h ? (long long)tls_last_index(h) : -1);
    return 1;
  }
  uint8_t* blob = nullptr;
  int64_t n = tls_get(h, tls_first_index(h), &blob);
  if (n <= 0) return 1;
  tls_free(blob);
  tls_close(h);
  printf("check_logstore OK (%lld entries, concurrent read/truncate)\n",
         (long long)kN);
  return 0;
}
