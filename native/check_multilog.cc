// Sanitizer exercise driver for the shared multi-group log engine
// (multilog.cc): concurrent per-group appenders + readers + a syncer +
// prefix truncation + GC, then reopen-and-verify every group.
// Run under TSAN and ASAN by `make -C native check-native`.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>
#include <zlib.h>

extern "C" {
struct tlm_handle;
tlm_handle* tlm_open(const char* dir, int64_t seg_max, char* err, int errlen);
void tlm_close(tlm_handle* h);
uint32_t tlm_register_group(tlm_handle* h, const char* name, char* err,
                            int errlen);
int64_t tlm_first(tlm_handle* h, uint32_t gid);
int64_t tlm_last(tlm_handle* h, uint32_t gid);
int64_t tlm_append(tlm_handle* h, uint32_t gid, const uint8_t* frames,
                   int64_t total, char* err, int errlen);
int tlm_sync(tlm_handle* h, char* err, int errlen);
int64_t tlm_sync_count(tlm_handle* h);
int64_t tlm_get(tlm_handle* h, uint32_t gid, int64_t index, uint8_t** out);
void tlm_free(uint8_t* buf);
int tlm_truncate_prefix(tlm_handle* h, uint32_t gid, int64_t first_kept);
int64_t tlm_gc(tlm_handle* h);
int64_t tlm_file_count(tlm_handle* h);
}

namespace {

constexpr size_t kHdr = 32;

std::string make_frame(int64_t index, int64_t term, const std::string& data) {
  std::string blob(kHdr, '\0');
  uint8_t* p = reinterpret_cast<uint8_t*>(blob.data());
  p[0] = 0xB8;
  p[1] = 1;
  memcpy(p + 4, &term, 8);
  memcpy(p + 12, &index, 8);
  uint32_t dl = static_cast<uint32_t>(data.size());
  memcpy(p + 24, &dl, 4);
  uLong c = crc32(0L, Z_NULL, 0);
  c = crc32(c, reinterpret_cast<const Bytef*>(data.data()), dl);
  uint32_t crc = static_cast<uint32_t>(c);
  memcpy(p + 28, &crc, 4);
  blob += data;
  uint32_t flen = static_cast<uint32_t>(blob.size());
  std::string frame(4, '\0');
  memcpy(frame.data(), &flen, 4);
  return frame + blob;
}

}  // namespace

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp/tpuraft_check_multilog";
  std::string cmd = std::string("rm -rf ") + dir;
  if (system(cmd.c_str()) != 0) return 2;
  char err[256] = {0};
  tlm_handle* h = tlm_open(dir, 1 << 16, err, sizeof(err));
  if (!h) {
    fprintf(stderr, "open failed: %s\n", err);
    return 1;
  }

  constexpr int kGroups = 8;
  constexpr int64_t kPerGroup = 1500;
  uint32_t gids[kGroups];
  for (int g = 0; g < kGroups; ++g) {
    std::string name = "grp" + std::to_string(g);
    gids[g] = tlm_register_group(h, name.c_str(), err, sizeof(err));
    if (!gids[g]) {
      fprintf(stderr, "register failed: %s\n", err);
      return 1;
    }
  }

  std::atomic<int64_t> appended[kGroups];
  for (auto& a : appended) a.store(0);
  std::atomic<bool> stop{false};

  std::vector<std::thread> appenders;
  for (int g = 0; g < kGroups; ++g) {
    appenders.emplace_back([&, g] {
      for (int64_t i = 1; i <= kPerGroup; ++i) {
        std::string f = make_frame(i, g + 1, "d" + std::to_string(i));
        char e[256];
        if (tlm_append(h, gids[g], (const uint8_t*)f.data(),
                       (int64_t)f.size(), e, sizeof(e)) != 1) {
          fprintf(stderr, "append g%d/%lld: %s\n", g, (long long)i, e);
          abort();
        }
        appended[g].store(i, std::memory_order_release);
      }
    });
  }

  std::thread syncer([&] {
    while (!stop.load(std::memory_order_acquire)) {
      char e[256];
      if (tlm_sync(h, e, sizeof(e)) != 0) {
        fprintf(stderr, "sync: %s\n", e);
        abort();
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  });

  std::vector<std::thread> readers;
  for (int t = 0; t < 2; ++t) {
    readers.emplace_back([&, t] {
      uint64_t n = 0;
      while (!stop.load(std::memory_order_acquire)) {
        int g = (int)(n % kGroups);
        int64_t hi = appended[g].load(std::memory_order_acquire);
        int64_t lo = tlm_first(h, gids[g]);
        if (hi >= lo && hi > 0) {
          int64_t idx = lo + (int64_t)((n * 131) % (uint64_t)(hi - lo + 1));
          uint8_t* blob = nullptr;
          int64_t r = tlm_get(h, gids[g], idx, &blob);
          if (r > 0) {
            int64_t got;
            memcpy(&got, blob + 12, 8);
            if (got != idx) {
              fprintf(stderr, "g%d idx %lld != %lld\n", g, (long long)got,
                      (long long)idx);
              abort();
            }
            tlm_free(blob);
          }
        }
        ++n;
      }
    });
  }

  std::thread truncator([&] {
    while (!stop.load(std::memory_order_acquire)) {
      for (int g = 0; g < kGroups; g += 2) {
        int64_t hi = appended[g].load(std::memory_order_acquire);
        if (hi > 400) tlm_truncate_prefix(h, gids[g], hi - 300);
      }
      tlm_gc(h);
      std::this_thread::sleep_for(std::chrono::milliseconds(7));
    }
  });

  for (auto& a : appenders) a.join();
  stop.store(true, std::memory_order_release);
  syncer.join();
  for (auto& r : readers) r.join();
  truncator.join();

  char e2[256];
  tlm_sync(h, e2, sizeof(e2));
  tlm_close(h);

  h = tlm_open(dir, 1 << 16, err, sizeof(err));
  if (!h) {
    fprintf(stderr, "reopen failed: %s\n", err);
    return 1;
  }
  for (int g = 0; g < kGroups; ++g) {
    std::string name = "grp" + std::to_string(g);
    uint32_t gid = tlm_register_group(h, name.c_str(), err, sizeof(err));
    if (tlm_last(h, gid) != kPerGroup) {
      fprintf(stderr, "g%d last %lld != %lld\n", g,
              (long long)tlm_last(h, gid), (long long)kPerGroup);
      return 1;
    }
    uint8_t* blob = nullptr;
    int64_t r = tlm_get(h, gid, tlm_first(h, gid), &blob);
    if (r <= 0) return 1;
    tlm_free(blob);
  }
  printf("check_multilog OK (%d groups x %lld entries, %lld fsync rounds, "
         "%lld files)\n",
         kGroups, (long long)kPerGroup, (long long)tlm_sync_count(h),
         (long long)tlm_file_count(h));
  tlm_close(h);
  return 0;
}
