// tpuraft native transport: epoll event-loop TCP engine for the RPC
// protocol plane.
//
// Reference parity: the role Netty's native epoll transport plays under
// SOFABolt (SURVEY.md §3.4 "Netty native transport"): a C event loop
// owning every socket — one listener multiplexing all raft groups, a
// pooled auto-reconnecting outbound connection per destination — with
// the Python asyncio runtime above it only ever touching complete
// frames.  Wire format is identical to tpuraft/rpc/tcp.py:
//
//   u32 payload_len | u64 seq | u8 flags | payload   (little-endian)
//
// so native and pure-Python endpoints interoperate on the same port.
//
// Threading model: one I/O thread runs epoll_wait and performs ALL
// socket reads/writes.  API calls from the host thread only mutate
// queues under the global context mutex and arm EPOLLOUT / write to a
// wakeup eventfd; completed inbound frames flow back through an event
// queue drained via tnt_next_event, with a notify eventfd the host can
// register in its own event loop (asyncio add_reader).  The two queues
// are the hand-off rings of the reference's Disruptor usage (SURVEY.md
// §3.4 "LMAX Disruptor" row).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr size_t kHdrSize = 13;  // u32 len + u64 seq + u8 flags
constexpr uint32_t kMaxFrame = 256u * 1024 * 1024;  // matches tcp.py
constexpr int kListenBacklog = 128;

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// The wire format is pinned little-endian (tcp.py's struct "<IQB"), so
// serialize explicitly rather than via native-endian memcpy.
uint32_t load_le32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

uint64_t load_le64(const uint8_t* p) {
  return static_cast<uint64_t>(load_le32(p)) |
         (static_cast<uint64_t>(load_le32(p + 4)) << 32);
}

void store_le32(char* p, uint32_t v) {
  p[0] = static_cast<char>(v & 0xff);
  p[1] = static_cast<char>((v >> 8) & 0xff);
  p[2] = static_cast<char>((v >> 16) & 0xff);
  p[3] = static_cast<char>((v >> 24) & 0xff);
}

void store_le64(char* p, uint64_t v) {
  store_le32(p, static_cast<uint32_t>(v & 0xffffffffu));
  store_le32(p + 4, static_cast<uint32_t>(v >> 32));
}

struct Conn {
  int64_t id = 0;
  int fd = -1;
  std::string endpoint;      // outbound: pool key "host:port"; inbound: peer
  bool outbound = false;
  bool connecting = false;   // nonblocking connect in flight
  std::string rbuf;          // inbound byte stream
  size_t roff = 0;           // parse offset into rbuf
  std::deque<std::string> wq;
  size_t woff = 0;           // bytes of wq.front() already written
  bool want_write = false;   // EPOLLOUT currently armed
};

struct Event {
  int type;                  // 1 = frame, 2 = closed
  int64_t conn_id;
  uint64_t seq;
  uint8_t flags;
  std::string payload;
  std::string endpoint;
};

struct Ctx {
  std::mutex mu;
  int ep = -1;               // epoll fd
  int wake_fd = -1;          // host -> io thread
  int notify_fd = -1;        // io thread -> host
  bool stopping = false;
  int64_t next_id = 1;
  std::map<int64_t, std::unique_ptr<Conn>> conns;
  std::map<std::string, int64_t> pool;   // outbound endpoint -> conn id
  std::map<int64_t, int> listeners;      // id -> listen fd
  // listeners parked after a persistent accept error (e.g. EMFILE),
  // re-armed once their deadline passes — avoids a level-triggered
  // busy-spin while the condition lasts
  std::map<int64_t, std::chrono::steady_clock::time_point> parked;
  std::deque<Event> events;
  std::thread io;

  ~Ctx() {
    for (auto& [id, c] : conns) {
      if (c->fd >= 0) close(c->fd);
    }
    for (auto& [id, fd] : listeners) close(fd);
    if (ep >= 0) close(ep);
    if (wake_fd >= 0) close(wake_fd);
    if (notify_fd >= 0) close(notify_fd);
  }
};

void notify(Ctx* c) {
  uint64_t one = 1;
  ssize_t r = write(c->notify_fd, &one, 8);
  (void)r;  // eventfd counter saturation is fine; host drains level-wise
}

void push_event(Ctx* c, Event ev) {
  c->events.push_back(std::move(ev));
  notify(c);
}

// Must hold c->mu.  Emits CLOSED and removes the connection.
void close_conn(Ctx* c, int64_t id) {
  auto it = c->conns.find(id);
  if (it == c->conns.end()) return;
  Conn* conn = it->second.get();
  Event ev;
  ev.type = 2;
  ev.conn_id = id;
  ev.seq = 0;
  ev.flags = 0;
  ev.endpoint = conn->endpoint;
  if (conn->outbound) {
    auto pit = c->pool.find(conn->endpoint);
    if (pit != c->pool.end() && pit->second == id) c->pool.erase(pit);
  }
  if (conn->fd >= 0) close(conn->fd);  // epoll deregisters automatically
  c->conns.erase(it);
  push_event(c, std::move(ev));
}

// Must hold c->mu.  Parse complete frames out of conn->rbuf.
void parse_frames(Ctx* c, Conn* conn, bool* fatal) {
  *fatal = false;
  for (;;) {
    size_t avail = conn->rbuf.size() - conn->roff;
    if (avail < kHdrSize) break;
    const uint8_t* p =
        reinterpret_cast<const uint8_t*>(conn->rbuf.data()) + conn->roff;
    uint32_t len = load_le32(p);
    uint64_t seq = load_le64(p + 4);
    uint8_t flags = p[12];
    if (len > kMaxFrame) {
      *fatal = true;  // protocol desync; unrecoverable stream position
      return;
    }
    if (avail < kHdrSize + len) break;
    Event ev;
    ev.type = 1;
    ev.conn_id = conn->id;
    ev.seq = seq;
    ev.flags = flags;
    ev.endpoint = conn->endpoint;
    ev.payload.assign(reinterpret_cast<const char*>(p) + kHdrSize, len);
    push_event(c, std::move(ev));
    conn->roff += kHdrSize + len;
  }
  // compact once the consumed prefix dominates, keeping appends O(1) am.
  if (conn->roff > 0 && conn->roff >= conn->rbuf.size() / 2 &&
      conn->rbuf.size() > 4096) {
    conn->rbuf.erase(0, conn->roff);
    conn->roff = 0;
  } else if (conn->roff == conn->rbuf.size()) {
    conn->rbuf.clear();
    conn->roff = 0;
  }
}

// Must hold c->mu.  Returns false if the connection died.
bool flush_writes(Ctx* c, Conn* conn) {
  while (!conn->wq.empty()) {
    const std::string& buf = conn->wq.front();
    ssize_t n = send(conn->fd, buf.data() + conn->woff,
                     buf.size() - conn->woff, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      return false;
    }
    conn->woff += static_cast<size_t>(n);
    if (conn->woff == conn->wq.front().size()) {
      conn->wq.pop_front();
      conn->woff = 0;
    }
  }
  bool want = !conn->wq.empty() || conn->connecting;
  if (want != conn->want_write) {
    conn->want_write = want;
    epoll_event ev{};
    ev.events = EPOLLIN | (want ? EPOLLOUT : 0u);
    ev.data.u64 = static_cast<uint64_t>(conn->id);
    epoll_ctl(c->ep, EPOLL_CTL_MOD, conn->fd, &ev);
  }
  return true;
}

void handle_readable(Ctx* c, Conn* conn) {
  char buf[65536];
  for (;;) {
    ssize_t n = recv(conn->fd, buf, sizeof(buf), 0);
    if (n > 0) {
      conn->rbuf.append(buf, static_cast<size_t>(n));
      bool fatal = false;
      parse_frames(c, conn, &fatal);
      if (fatal) {
        close_conn(c, conn->id);
        return;
      }
      if (n < static_cast<ssize_t>(sizeof(buf))) return;  // drained
      continue;
    }
    if (n == 0) {  // peer closed
      close_conn(c, conn->id);
      return;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
    if (errno == EINTR) continue;
    close_conn(c, conn->id);
    return;
  }
}

void handle_accept(Ctx* c, int64_t listener_id, int lfd) {
  for (;;) {
    sockaddr_in addr{};
    socklen_t alen = sizeof(addr);
    int fd = accept4(lfd, reinterpret_cast<sockaddr*>(&addr), &alen,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      if (errno == EINTR || errno == ECONNABORTED) continue;
      // persistent failure (EMFILE/ENFILE/...): park the listener so
      // level-triggered epoll doesn't busy-spin while it lasts
      epoll_ctl(c->ep, EPOLL_CTL_DEL, lfd, nullptr);
      c->parked[listener_id] =
          std::chrono::steady_clock::now() + std::chrono::milliseconds(100);
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_unique<Conn>();
    conn->id = c->next_id++;
    conn->fd = fd;
    char ip[64];
    inet_ntop(AF_INET, &addr.sin_addr, ip, sizeof(ip));
    conn->endpoint = std::string(ip) + ":" + std::to_string(
        ntohs(addr.sin_port));
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = static_cast<uint64_t>(conn->id);
    if (epoll_ctl(c->ep, EPOLL_CTL_ADD, fd, &ev) != 0) {
      close(fd);
      continue;
    }
    c->conns.emplace(conn->id, std::move(conn));
  }
}

void io_loop(Ctx* c) {
  epoll_event evs[64];
  for (;;) {
    int timeout_ms = 1000;
    {
      std::lock_guard<std::mutex> g0(c->mu);
      if (!c->parked.empty()) timeout_ms = 50;
    }
    int n = epoll_wait(c->ep, evs, 64, timeout_ms);
    std::lock_guard<std::mutex> g(c->mu);
    if (c->stopping) return;
    // re-arm listeners parked after persistent accept errors
    if (!c->parked.empty()) {
      auto now = std::chrono::steady_clock::now();
      for (auto it = c->parked.begin(); it != c->parked.end();) {
        if (now < it->second) {
          ++it;
          continue;
        }
        auto lit = c->listeners.find(it->first);
        if (lit != c->listeners.end()) {
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.u64 = static_cast<uint64_t>(it->first);
          epoll_ctl(c->ep, EPOLL_CTL_ADD, lit->second, &ev);
        }
        it = c->parked.erase(it);
      }
    }
    for (int i = 0; i < n; ++i) {
      uint64_t id64 = evs[i].data.u64;
      if (id64 == 0) {  // wakeup eventfd
        uint64_t junk;
        while (read(c->wake_fd, &junk, 8) == 8) {
        }
        continue;
      }
      int64_t id = static_cast<int64_t>(id64);
      auto lit = c->listeners.find(id);
      if (lit != c->listeners.end()) {
        handle_accept(c, id, lit->second);
        continue;
      }
      auto it = c->conns.find(id);
      if (it == c->conns.end()) continue;  // closed earlier this batch
      Conn* conn = it->second.get();
      uint32_t flags = evs[i].events;
      if (conn->connecting && (flags & (EPOLLOUT | EPOLLERR | EPOLLHUP))) {
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(conn->fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        if (soerr != 0) {
          close_conn(c, id);
          continue;
        }
        conn->connecting = false;
        if (!flush_writes(c, conn)) {
          close_conn(c, id);
          continue;
        }
      } else if (flags & (EPOLLERR | EPOLLHUP)) {
        // drain any final bytes first, then close
        handle_readable(c, conn);
        if (c->conns.count(id)) close_conn(c, id);
        continue;
      }
      if (flags & EPOLLIN) {
        handle_readable(c, conn);
        if (!c->conns.count(id)) continue;
      }
      if (flags & EPOLLOUT) {
        if (!flush_writes(c, conn)) close_conn(c, id);
      }
    }
  }
}

void wake(Ctx* c) {
  uint64_t one = 1;
  ssize_t r = write(c->wake_fd, &one, 8);
  (void)r;
}

std::string frame(uint64_t seq, uint8_t flags, const uint8_t* payload,
                  int64_t len) {
  std::string out;
  out.reserve(kHdrSize + static_cast<size_t>(len));
  char hdr[kHdrSize];
  store_le32(hdr, static_cast<uint32_t>(len));
  store_le64(hdr + 4, seq);
  hdr[12] = static_cast<char>(flags);
  out.append(hdr, kHdrSize);
  if (len > 0) out.append(reinterpret_cast<const char*>(payload),
                          static_cast<size_t>(len));
  return out;
}

bool resolve(const std::string& host, int port, sockaddr_in* out,
             std::string* emsg) {
  memset(out, 0, sizeof(*out));
  out->sin_family = AF_INET;
  out->sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &out->sin_addr) == 1) return true;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  int rc = getaddrinfo(host.c_str(), nullptr, &hints, &res);
  if (rc != 0 || !res) {
    *emsg = std::string("resolve ") + host + ": " + gai_strerror(rc);
    if (res) freeaddrinfo(res);
    return false;
  }
  out->sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

}  // namespace

extern "C" {

void* tnt_create(char* err, int errlen) {
  auto c = std::make_unique<Ctx>();
  c->ep = epoll_create1(EPOLL_CLOEXEC);
  c->wake_fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  c->notify_fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  if (c->ep < 0 || c->wake_fd < 0 || c->notify_fd < 0) {
    set_err(err, errlen, std::string("create: ") + strerror(errno));
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // id 0 = wakeup fd
  if (epoll_ctl(c->ep, EPOLL_CTL_ADD, c->wake_fd, &ev) != 0) {
    set_err(err, errlen, std::string("epoll wakeup: ") + strerror(errno));
    return nullptr;
  }
  Ctx* raw = c.release();
  raw->io = std::thread(io_loop, raw);
  return raw;
}

void tnt_destroy(void* h) {
  auto* c = static_cast<Ctx*>(h);
  if (!c) return;
  {
    std::lock_guard<std::mutex> g(c->mu);
    c->stopping = true;
  }
  wake(c);
  if (c->io.joinable()) c->io.join();
  delete c;
}

int tnt_notify_fd(void* h) {
  return static_cast<Ctx*>(h)->notify_fd;
}

// Returns the bound port, or -1.
int tnt_listen(void* h, const char* host, int port, char* err, int errlen) {
  auto* c = static_cast<Ctx*>(h);
  sockaddr_in addr;
  std::string emsg;
  if (!resolve(host, port, &addr, &emsg)) {
    set_err(err, errlen, emsg);
    return -1;
  }
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    set_err(err, errlen, std::string("socket: ") + strerror(errno));
    return -1;
  }
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, kListenBacklog) != 0) {
    set_err(err, errlen, std::string("bind/listen: ") + strerror(errno));
    close(fd);
    return -1;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);
  std::lock_guard<std::mutex> g(c->mu);
  int64_t id = c->next_id++;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = static_cast<uint64_t>(id);
  if (epoll_ctl(c->ep, EPOLL_CTL_ADD, fd, &ev) != 0) {
    set_err(err, errlen, std::string("epoll add: ") + strerror(errno));
    close(fd);
    return -1;
  }
  c->listeners.emplace(id, fd);
  return ntohs(bound.sin_port);
}

// Queue a frame to `endpoint` ("host:port"), creating/reusing the pooled
// outbound connection.  Returns the conn id used (>0), or -1.
int64_t tnt_send_to(void* h, const char* endpoint, uint64_t seq,
                    uint8_t flags, const uint8_t* payload, int64_t len,
                    char* err, int errlen) {
  auto* c = static_cast<Ctx*>(h);
  if (len < 0 || static_cast<uint64_t>(len) > kMaxFrame) {
    set_err(err, errlen, "oversized frame");
    return -1;
  }
  std::string ep(endpoint);
  auto colon = ep.rfind(':');
  if (colon == std::string::npos) {
    set_err(err, errlen, "endpoint must be host:port");
    return -1;
  }
  std::unique_lock<std::mutex> g(c->mu);
  auto pit = c->pool.find(ep);
  Conn* conn = nullptr;
  if (pit != c->pool.end()) {
    auto it = c->conns.find(pit->second);
    if (it != c->conns.end()) conn = it->second.get();
  }
  if (conn == nullptr) {
    // resolve only on new-connection creation, outside the lock (may
    // hit DNS; the pooled fast path above never pays for it)
    g.unlock();
    sockaddr_in addr;
    std::string emsg;
    if (!resolve(ep.substr(0, colon), atoi(ep.c_str() + colon + 1), &addr,
                 &emsg)) {
      set_err(err, errlen, emsg);
      return -1;
    }
    g.lock();
    // another caller may have created the connection meanwhile
    pit = c->pool.find(ep);
    if (pit != c->pool.end()) {
      auto it = c->conns.find(pit->second);
      if (it != c->conns.end()) conn = it->second.get();
    }
    if (conn != nullptr) {
      conn->wq.push_back(frame(seq, flags, payload, len));
      if (!conn->want_write) {
        conn->want_write = true;
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u64 = static_cast<uint64_t>(conn->id);
        epoll_ctl(c->ep, EPOLL_CTL_MOD, conn->fd, &ev);
      }
      return conn->id;
    }
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) {
      set_err(err, errlen, std::string("socket: ") + strerror(errno));
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc != 0 && errno != EINPROGRESS) {
      set_err(err, errlen, std::string("connect: ") + strerror(errno));
      close(fd);
      return -1;
    }
    auto nc = std::make_unique<Conn>();
    nc->id = c->next_id++;
    nc->fd = fd;
    nc->endpoint = ep;
    nc->outbound = true;
    nc->connecting = (rc != 0);
    nc->want_write = true;  // EPOLLOUT armed below
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u64 = static_cast<uint64_t>(nc->id);
    if (epoll_ctl(c->ep, EPOLL_CTL_ADD, fd, &ev) != 0) {
      set_err(err, errlen, std::string("epoll add: ") + strerror(errno));
      close(fd);
      return -1;
    }
    conn = nc.get();
    c->pool[ep] = nc->id;
    c->conns.emplace(nc->id, std::move(nc));
  }
  conn->wq.push_back(frame(seq, flags, payload, len));
  if (!conn->want_write) {
    conn->want_write = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u64 = static_cast<uint64_t>(conn->id);
    epoll_ctl(c->ep, EPOLL_CTL_MOD, conn->fd, &ev);
  }
  return conn->id;
}

// Queue a frame on an existing connection (server responses).  Returns 0,
// or -1 if the connection is gone (peer will retry — matches tcp.py).
int tnt_send_conn(void* h, int64_t conn_id, uint64_t seq, uint8_t flags,
                  const uint8_t* payload, int64_t len) {
  auto* c = static_cast<Ctx*>(h);
  if (len < 0 || static_cast<uint64_t>(len) > kMaxFrame) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->conns.find(conn_id);
  if (it == c->conns.end()) return -1;
  Conn* conn = it->second.get();
  conn->wq.push_back(frame(seq, flags, payload, len));
  if (!conn->want_write) {
    conn->want_write = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u64 = static_cast<uint64_t>(conn->id);
    epoll_ctl(c->ep, EPOLL_CTL_MOD, conn->fd, &ev);
  }
  return 0;
}

// Close and forget the pooled outbound connection to `endpoint` (fails
// its in-flight requests with a CLOSED event).
int tnt_drop_endpoint(void* h, const char* endpoint) {
  auto* c = static_cast<Ctx*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  auto pit = c->pool.find(endpoint);
  if (pit == c->pool.end()) return 0;
  close_conn(c, pit->second);
  return 1;
}

// Dequeue one event.  Returns 1 and fills the out-params (payload is
// malloc'd, free with tnt_free), or 0 if the queue is empty.  Event
// types: 1 = frame {conn_id, seq, flags, payload}, 2 = connection
// closed {conn_id, endpoint}.
int tnt_next_event(void* h, int* type, int64_t* conn_id, uint64_t* seq,
                   uint8_t* flags, uint8_t** payload, int64_t* len,
                   char* endpoint_out, int endpoint_cap) {
  auto* c = static_cast<Ctx*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  if (c->events.empty()) {
    // level-style notify: clear the counter only when fully drained, so
    // a host read of the eventfd between notifies can't strand events
    uint64_t junk;
    while (read(c->notify_fd, &junk, 8) == 8) {
    }
    return 0;
  }
  Event& ev = c->events.front();
  *type = ev.type;
  *conn_id = ev.conn_id;
  *seq = ev.seq;
  *flags = ev.flags;
  *len = static_cast<int64_t>(ev.payload.size());
  uint8_t* out = static_cast<uint8_t*>(
      malloc(ev.payload.size() ? ev.payload.size() : 1));
  if (!out) return 0;  // retry later; event stays queued
  memcpy(out, ev.payload.data(), ev.payload.size());
  *payload = out;
  if (endpoint_out && endpoint_cap > 0) {
    snprintf(endpoint_out, static_cast<size_t>(endpoint_cap), "%s",
             ev.endpoint.c_str());
  }
  c->events.pop_front();
  return 1;
}

void tnt_free(uint8_t* p) { free(p); }

}  // extern "C"
