// tpuraft native log storage engine.
//
// Reference parity: the role RocksDB (C++, via rocksdbjni) plays under
// core:storage/impl/RocksDBLogStorage — the durable raft log engine behind
// the Python LogStorage SPI (SURVEY.md §3.4 "Native / non-Java components").
// Where the reference keys a general-purpose LSM by 8-byte big-endian index,
// this engine is purpose-built for raft's access pattern: append-mostly,
// contiguous reads, prefix truncation at snapshot, suffix truncation on
// conflict.
//
// On-disk format — IDENTICAL to tpuraft/storage/log_storage.py FileLogStorage
// (the two engines are interchangeable on the same directory):
//   seg_<first_index>.log : repeated [ u32le frame_len | entry blob ]
//   meta                  : i64le first_log_index (atomic tmp+rename)
//   conf.idx              : packed i64le indexes of CONFIGURATION entries
// Entry blob layout (tpuraft/entity.py _HDR "<BBHqqHHII"):
//   magic(1)=0xB8 type(1) rsv(2) term(8) index(8) npeers(2) nold(2)
//   data_len(4) crc32(4) | peers_blob | data
//   crc32 = zlib crc over data first, then peers_blob.
//
// Exposed as a C ABI for ctypes (tpuraft/storage/native_log.py).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>
#include <zlib.h>

namespace {

constexpr uint8_t kMagic = 0xB8;
constexpr uint8_t kTypeConfiguration = 2;
constexpr size_t kHdrSize = 32;
constexpr size_t kFrameSize = 4;  // u32 length prefix

// -- little-endian unaligned loads (format is LE; TPU hosts are LE) ---------

uint16_t load_u16(const uint8_t* p) { uint16_t v; memcpy(&v, p, 2); return v; }
uint32_t load_u32(const uint8_t* p) { uint32_t v; memcpy(&v, p, 4); return v; }
int64_t load_i64(const uint8_t* p) { int64_t v; memcpy(&v, p, 8); return v; }

struct EntryHeader {
  uint8_t type;
  int64_t term;
  int64_t index;
  uint16_t peers_len;
  uint32_t data_len;
  uint32_t crc;
};

// Parses + validates one entry blob. Returns false on any corruption.
bool parse_entry(const uint8_t* blob, size_t len, EntryHeader* out,
                 bool verify_crc) {
  if (len < kHdrSize) return false;
  if (blob[0] != kMagic) return false;
  out->type = blob[1];
  out->term = load_i64(blob + 4);
  out->index = load_i64(blob + 12);
  out->peers_len = load_u16(blob + 20);
  out->data_len = load_u32(blob + 24);
  out->crc = load_u32(blob + 28);
  if (kHdrSize + out->peers_len + (size_t)out->data_len != len) return false;
  if (verify_crc) {
    const uint8_t* peers = blob + kHdrSize;
    const uint8_t* data = peers + out->peers_len;
    uLong c = crc32(0L, Z_NULL, 0);
    c = crc32(c, data, out->data_len);
    c = crc32(c, peers, out->peers_len);
    if ((uint32_t)c != out->crc) return false;
  }
  return true;
}

bool fsync_fd(int fd) { return ::fsync(fd) == 0; }

bool fsync_dir(const std::string& dir) {
  int fd = ::open(dir.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = fsync_fd(fd);
  ::close(fd);
  return ok;
}

bool write_all(int fd, const uint8_t* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::write(fd, buf, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += n;
    len -= (size_t)n;
  }
  return true;
}

// Atomic small-file write: tmp + fsync + rename + dir fsync.
bool atomic_write_file(const std::string& dir, const std::string& name,
                       const uint8_t* buf, size_t len) {
  std::string tmp = dir + "/" + name + ".tmp";
  std::string dst = dir + "/" + name;
  int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  bool ok = write_all(fd, buf, len) && fsync_fd(fd);
  ::close(fd);
  if (!ok) return false;
  if (::rename(tmp.c_str(), dst.c_str()) != 0) return false;
  return fsync_dir(dir);
}

// -- one append-only segment file with an in-memory offset index ------------

struct Segment {
  std::string path;
  int64_t first_index = 0;
  std::vector<int64_t> offsets;  // offsets[i] = file offset of first_index+i
  int64_t size = 0;
  int fd = -1;

  int64_t last_index() const {
    return first_index + (int64_t)offsets.size() - 1;
  }

  bool open_file(bool create) {
    fd = ::open(path.c_str(), O_RDWR | (create ? O_CREAT : 0), 0644);
    return fd >= 0;
  }

  // Rebuild the offset index; truncate a torn tail write if found.
  bool scan() {
    struct stat st;
    if (::fstat(fd, &st) != 0) return false;
    int64_t end = st.st_size;
    std::vector<uint8_t> buf((size_t)end);
    if (end > 0) {
      ssize_t n = ::pread(fd, buf.data(), (size_t)end, 0);
      if (n != end) return false;
    }
    int64_t off = 0, good_end = 0;
    while (off + (int64_t)kFrameSize <= end) {
      uint32_t flen = load_u32(buf.data() + off);
      if (off + (int64_t)kFrameSize + flen > end) break;  // torn write
      EntryHeader h;
      if (!parse_entry(buf.data() + off + kFrameSize, flen, &h, true)) break;
      offsets.push_back(off);
      off += (int64_t)kFrameSize + flen;
      good_end = off;
    }
    if (good_end < end) {
      if (::ftruncate(fd, good_end) != 0) return false;
    }
    size = good_end;
    return true;
  }

  // Returns the framed blob length at `index`, copied into out (malloc'd).
  int64_t read_entry(int64_t index, uint8_t** out) const {
    int64_t off = offsets[(size_t)(index - first_index)];
    uint8_t hdr[kFrameSize];
    if (::pread(fd, hdr, kFrameSize, off) != (ssize_t)kFrameSize) return -1;
    uint32_t flen = load_u32(hdr);
    uint8_t* blob = (uint8_t*)malloc(flen);
    if (!blob) return -1;
    if (::pread(fd, blob, flen, off + kFrameSize) != (ssize_t)flen) {
      free(blob);
      return -1;
    }
    *out = blob;
    return (int64_t)flen;
  }

  bool truncate_to(int64_t last_index_kept) {
    int64_t n_keep = last_index_kept - first_index + 1;
    if (n_keep >= (int64_t)offsets.size()) return true;
    int64_t new_size = n_keep > 0 ? offsets[(size_t)n_keep] : 0;
    if (::ftruncate(fd, new_size) != 0) return false;
    if (!fsync_fd(fd)) return false;
    offsets.resize((size_t)n_keep);
    size = new_size;
    return true;
  }

  void close_file() {
    if (fd >= 0) {
      ::close(fd);
      fd = -1;
    }
  }

  void remove_file() {
    close_file();
    ::unlink(path.c_str());
  }
};

}  // namespace

// -- the engine --------------------------------------------------------------

struct tls_handle {
  std::string dir;
  int64_t seg_max;
  int64_t first = 1;
  std::vector<std::unique_ptr<Segment>> segments;
  std::vector<int64_t> conf_indexes;
  std::mutex mu;
  std::string last_error;

  int64_t last_index_locked() const {
    if (segments.empty()) return first - 1;
    return segments.back()->last_index();
  }

  bool save_meta() {
    uint8_t buf[8];
    memcpy(buf, &first, 8);
    return atomic_write_file(dir, "meta", buf, 8);
  }

  void load_meta() {
    int fd = ::open((dir + "/meta").c_str(), O_RDONLY);
    if (fd < 0) return;
    uint8_t buf[8];
    if (::read(fd, buf, 8) == 8) first = load_i64(buf);
    ::close(fd);
  }

  bool rewrite_conf() {
    std::vector<uint8_t> buf(conf_indexes.size() * 8);
    for (size_t i = 0; i < conf_indexes.size(); ++i)
      memcpy(buf.data() + i * 8, &conf_indexes[i], 8);
    return atomic_write_file(dir, "conf.idx", buf.data(), buf.size());
  }

  void load_conf() {
    conf_indexes.clear();
    int fd = ::open((dir + "/conf.idx").c_str(), O_RDONLY);
    if (fd < 0) return;
    struct stat st;
    if (::fstat(fd, &st) == 0 && st.st_size >= 8) {
      std::vector<uint8_t> buf((size_t)st.st_size);
      ssize_t n = ::read(fd, buf.data(), buf.size());
      int64_t last = last_index_locked();
      for (ssize_t off = 0; off + 8 <= n; off += 8) {
        int64_t idx = load_i64(buf.data() + off);
        if (idx >= first && idx <= last) conf_indexes.push_back(idx);
      }
    }
    ::close(fd);
  }

  Segment* find_segment(int64_t index) {
    size_t lo = 0, hi = segments.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      Segment* s = segments[mid].get();
      if (index < s->first_index) {
        hi = mid;
      } else if (index > s->last_index()) {
        lo = mid + 1;
      } else {
        return s;
      }
    }
    return nullptr;
  }
};

extern "C" {

tls_handle* tls_open(const char* dir_path, int64_t seg_max_bytes,
                     char* errbuf, int errlen) {
  auto set_err = [&](const std::string& msg) {
    if (errbuf && errlen > 0) {
      snprintf(errbuf, (size_t)errlen, "%s", msg.c_str());
    }
  };
  auto h = std::make_unique<tls_handle>();
  h->dir = dir_path;
  h->seg_max = seg_max_bytes > 0 ? seg_max_bytes : (64LL << 20);
  if (::mkdir(dir_path, 0755) != 0 && errno != EEXIST) {
    set_err(std::string("mkdir failed: ") + strerror(errno));
    return nullptr;
  }
  h->load_meta();

  // Collect seg_<first>.log names sorted by first index.
  std::vector<std::pair<int64_t, std::string>> names;
  DIR* d = ::opendir(dir_path);
  if (!d) {
    set_err(std::string("opendir failed: ") + strerror(errno));
    return nullptr;
  }
  while (struct dirent* ent = ::readdir(d)) {
    std::string n = ent->d_name;
    if (n.rfind("seg_", 0) == 0 && n.size() > 8 &&
        n.compare(n.size() - 4, 4, ".log") == 0) {
      names.emplace_back(strtoll(n.c_str() + 4, nullptr, 10), n);
    }
  }
  ::closedir(d);
  std::sort(names.begin(), names.end());

  bool drop_rest = false;
  for (auto& [fidx, name] : names) {
    auto seg = std::make_unique<Segment>();
    seg->path = h->dir + "/" + name;
    seg->first_index = fidx;
    if (!seg->open_file(false)) continue;
    if (!seg->scan()) {
      set_err("segment scan failed: " + seg->path);
      return nullptr;
    }
    // Stale: fully below first_log_index — crash mid truncate_prefix
    // (meta saved, file not yet deleted).
    bool stale = seg->first_index < h->first &&
                 (seg->offsets.empty() || seg->last_index() < h->first);
    if (drop_rest || stale) {
      seg->remove_file();
      continue;
    }
    if (seg->offsets.empty() ||
        (!h->segments.empty() &&
         seg->first_index != h->segments.back()->last_index() + 1)) {
      // Empty (torn) segment or a hole from a torn multi-segment batch
      // append: everything from here on is unreachable.
      seg->remove_file();
      drop_rest = true;
      continue;
    }
    h->segments.push_back(std::move(seg));
  }
  h->load_conf();
  return h.release();
}

void tls_close(tls_handle* h) {
  if (!h) return;
  {
    std::lock_guard<std::mutex> g(h->mu);
    for (auto& s : h->segments) s->close_file();
    h->segments.clear();
  }
  delete h;
}

int64_t tls_first_index(tls_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return h->first;
}

int64_t tls_last_index(tls_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return h->last_index_locked();
}

// Returns blob length and sets *out (caller frees with tls_free), or -1 if
// the index is absent.
int64_t tls_get(tls_handle* h, int64_t index, uint8_t** out) {
  std::lock_guard<std::mutex> g(h->mu);
  if (index < h->first) return -1;
  Segment* s = h->find_segment(index);
  if (!s) return -1;
  return s->read_entry(index, out);
}

void tls_free(uint8_t* buf) { free(buf); }

// frames = concatenated [u32le len | entry blob]; returns entries appended
// or -1 (error text in errbuf).
int64_t tls_append(tls_handle* h, const uint8_t* frames, int64_t total,
                   int sync, char* errbuf, int errlen) {
  auto fail = [&](const std::string& msg) -> int64_t {
    if (errbuf && errlen > 0) snprintf(errbuf, (size_t)errlen, "%s", msg.c_str());
    return -1;
  };
  std::lock_guard<std::mutex> g(h->mu);

  // Parse every frame up front: indexes, types, rotation points.
  struct Frame {
    int64_t off;  // offset in `frames`
    int64_t len;  // frame (incl. length prefix) size
    EntryHeader hdr;
  };
  std::vector<Frame> parsed;
  int64_t expected = h->last_index_locked() + 1;
  int64_t off = 0;
  while (off < total) {
    if (off + (int64_t)kFrameSize > total) return fail("truncated frame header");
    uint32_t flen = load_u32(frames + off);
    if (off + (int64_t)kFrameSize + flen > total) return fail("truncated frame");
    Frame f;
    f.off = off;
    f.len = (int64_t)kFrameSize + flen;
    if (!parse_entry(frames + off + kFrameSize, flen, &f.hdr, false))
      return fail("bad entry blob in append batch");
    if (f.hdr.index != expected)
      return fail("non-contiguous append: have last=" +
                  std::to_string(expected - 1) + ", got " +
                  std::to_string(f.hdr.index));
    ++expected;
    parsed.push_back(f);
    off += f.len;
  }
  if (parsed.empty()) return 0;

  // Write contiguous runs, rotating segments at seg_max.  One write() per
  // touched segment (the reference batches via RocksDB WriteBatch).  The
  // in-memory index (offsets / conf_indexes) is only updated after the
  // bytes hit the fd, so a failed write leaves the index consistent with
  // the durable prefix.
  std::vector<Segment*> touched;
  bool new_conf = false;
  size_t i = 0;
  while (i < parsed.size()) {
    if (h->segments.empty() || h->segments.back()->size >= h->seg_max) {
      auto seg = std::make_unique<Segment>();
      seg->first_index = parsed[i].hdr.index;
      seg->path = h->dir + "/seg_" + std::to_string(seg->first_index) + ".log";
      if (!seg->open_file(true)) return fail("segment create failed");
      if (!fsync_dir(h->dir)) return fail("dir fsync failed");
      h->segments.push_back(std::move(seg));
    }
    Segment* cur = h->segments.back().get();
    // Greedy: take frames until rotation is due.
    int64_t run_start = parsed[i].off;
    int64_t run_len = 0;
    int64_t fill = cur->size;
    size_t j = i;
    while (j < parsed.size() && (run_len == 0 || fill < h->seg_max)) {
      fill += parsed[j].len;
      run_len += parsed[j].len;
      ++j;
    }
    if (::lseek(cur->fd, cur->size, SEEK_SET) < 0)
      return fail("seek failed");
    if (!write_all(cur->fd, frames + run_start, (size_t)run_len))
      return fail(std::string("write failed: ") + strerror(errno));
    int64_t off_in_seg = cur->size;
    for (size_t k = i; k < j; ++k) {
      cur->offsets.push_back(off_in_seg);
      off_in_seg += parsed[k].len;
      if (parsed[k].hdr.type == kTypeConfiguration) {
        h->conf_indexes.push_back(parsed[k].hdr.index);
        new_conf = true;
      }
    }
    cur->size = fill;
    if (touched.empty() || touched.back() != cur) touched.push_back(cur);
    i = j;
  }
  if (new_conf) {
    // Sidecar BEFORE the entry fsync (see FileLogStorage.append_entries).
    if (!h->rewrite_conf()) return fail("conf sidecar write failed");
  }
  if (sync) {
    // fsync oldest-first so a crash leaves a prefix, never a hole.
    for (Segment* s : touched)
      if (!fsync_fd(s->fd)) return fail("fsync failed");
  }
  return (int64_t)parsed.size();
}

int tls_truncate_prefix(tls_handle* h, int64_t first_kept) {
  std::lock_guard<std::mutex> g(h->mu);
  if (first_kept <= h->first) return 0;
  h->first = first_kept;
  if (!h->save_meta()) return -1;
  while (!h->segments.empty() &&
         h->segments.front()->last_index() < first_kept) {
    h->segments.front()->remove_file();
    h->segments.erase(h->segments.begin());
  }
  if (!h->conf_indexes.empty() && h->conf_indexes.front() < first_kept) {
    std::vector<int64_t> kept;
    for (int64_t i : h->conf_indexes)
      if (i >= first_kept) kept.push_back(i);
    h->conf_indexes = std::move(kept);
    if (!h->rewrite_conf()) return -1;
  }
  return 0;
}

int tls_truncate_suffix(tls_handle* h, int64_t last_kept) {
  std::lock_guard<std::mutex> g(h->mu);
  while (!h->segments.empty() &&
         h->segments.back()->first_index > last_kept) {
    h->segments.back()->remove_file();
    h->segments.pop_back();
  }
  if (!h->segments.empty() && !h->segments.back()->truncate_to(last_kept))
    return -1;
  if (!h->conf_indexes.empty() && h->conf_indexes.back() > last_kept) {
    std::vector<int64_t> kept;
    for (int64_t i : h->conf_indexes)
      if (i <= last_kept) kept.push_back(i);
    h->conf_indexes = std::move(kept);
    if (!h->rewrite_conf()) return -1;
  }
  return 0;
}

int tls_reset(tls_handle* h, int64_t next_index) {
  std::lock_guard<std::mutex> g(h->mu);
  for (auto& s : h->segments) s->remove_file();
  h->segments.clear();
  h->first = next_index;
  h->conf_indexes.clear();
  if (!h->rewrite_conf()) return -1;
  if (!h->save_meta()) return -1;
  return 0;
}

int64_t tls_conf_count(tls_handle* h) {
  std::lock_guard<std::mutex> g(h->mu);
  return (int64_t)h->conf_indexes.size();
}

int64_t tls_conf_indexes(tls_handle* h, int64_t* out, int64_t cap) {
  std::lock_guard<std::mutex> g(h->mu);
  int64_t n = std::min<int64_t>(cap, (int64_t)h->conf_indexes.size());
  for (int64_t i = 0; i < n; ++i) out[i] = h->conf_indexes[(size_t)i];
  return n;
}

}  // extern "C"
