// Sanitizer exercise driver for the KV storage engine (kvstore.cc).
// Concurrent writers / readers / scanner / checkpointer over the real
// C ABI, then reopen-and-verify.  Run under TSAN and ASAN by
// `make -C native check-native` (SURVEY.md §6 race-detection row).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* tkv_open(const char* dir, int sync, int64_t ckpt_wal_bytes,
               char* err, int errlen);
void* tkv_open2(const char* dir, int sync, int64_t ckpt_wal_bytes,
                int64_t memtable_budget, int64_t max_runs,
                char* err, int errlen);
int64_t tkv_run_count(void* h);
void tkv_close(void* h);
void tkv_free(uint8_t* p);
int tkv_apply_batch(void* h, const uint8_t* ops, int64_t len,
                    char* err, int errlen);
int64_t tkv_get(void* h, int col, const uint8_t* k, int64_t kl,
                uint8_t** out);
int64_t tkv_scan(void* h, int col, const uint8_t* start, int64_t sl,
                 const uint8_t* end, int64_t el, int64_t limit,
                 int with_values, int reverse, uint8_t** out);
int tkv_checkpoint(void* h, char* err, int errlen);
int64_t tkv_count(void* h, int col);
}

namespace {

// op(1) col(1) klen(4) key vlen(4) val
std::string put_op(const std::string& k, const std::string& v) {
  std::string s;
  s.push_back(1);
  s.push_back(0);
  uint32_t kl = k.size(), vl = v.size();
  s.append(reinterpret_cast<char*>(&kl), 4);
  s += k;
  s.append(reinterpret_cast<char*>(&vl), 4);
  s += v;
  return s;
}

}  // namespace

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp/tpuraft_check_kvstore";
  std::string cmd = std::string("rm -rf ") + dir;
  if (system(cmd.c_str()) != 0) return 2;
  char err[256] = {0};
  void* h = tkv_open(dir, 0 /*no fsync: sanitizer speed*/, 1 << 16,
                     err, sizeof(err));
  if (!h) {
    fprintf(stderr, "open failed: %s\n", err);
    return 1;
  }

  constexpr int kWriters = 3;
  constexpr int kPerWriter = 2000;
  std::atomic<bool> stop{false};

  std::vector<std::thread> writers;
  for (int w = 0; w < kWriters; ++w) {
    writers.emplace_back([&, w] {
      for (int i = 0; i < kPerWriter; ++i) {
        std::string k = "k" + std::to_string(w) + "-" + std::to_string(i);
        std::string ops = put_op(k, "v" + std::to_string(i));
        char e[256];
        if (tkv_apply_batch(h, reinterpret_cast<const uint8_t*>(ops.data()),
                            static_cast<int64_t>(ops.size()), e,
                            sizeof(e)) != 0) {
          fprintf(stderr, "put failed: %s\n", e);
          abort();
        }
      }
    });
  }

  std::thread reader([&] {
    uint64_t i = 0;
    while (!stop.load(std::memory_order_acquire)) {
      std::string k = "k0-" + std::to_string(i++ % kPerWriter);
      uint8_t* out = nullptr;
      int64_t n = tkv_get(h, 0, reinterpret_cast<const uint8_t*>(k.data()),
                          static_cast<int64_t>(k.size()), &out);
      if (n >= 0) tkv_free(out);
    }
  });

  std::thread scanner([&] {
    while (!stop.load(std::memory_order_acquire)) {
      uint8_t* out = nullptr;
      int64_t n = tkv_scan(h, 0, nullptr, 0, nullptr, 0, 64, 1, 0, &out);
      if (n >= 0) tkv_free(out);
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });

  std::thread ckpt([&] {
    while (!stop.load(std::memory_order_acquire)) {
      char e[256];
      tkv_checkpoint(h, e, sizeof(e));
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
  });

  for (auto& w : writers) w.join();
  stop.store(true, std::memory_order_release);
  reader.join();
  scanner.join();
  ckpt.join();

  int64_t n = tkv_count(h, 0);
  if (n != kWriters * kPerWriter) {
    fprintf(stderr, "count %lld != %d\n", (long long)n,
            kWriters * kPerWriter);
    return 1;
  }
  tkv_close(h);
  // reopen: checkpoint + WAL replay must reconstruct everything
  h = tkv_open(dir, 0, 1 << 16, err, sizeof(err));
  if (!h) {
    fprintf(stderr, "reopen failed: %s\n", err);
    return 1;
  }
  if (tkv_count(h, 0) != kWriters * kPerWriter) {
    fprintf(stderr, "reopen count %lld\n", (long long)tkv_count(h, 0));
    return 1;
  }
  tkv_close(h);
  printf("check_kvstore OK (%d entries, concurrent write/read/scan/ckpt)\n",
         kWriters * kPerWriter);

  // -- LSM phase: spills + background compactor under the sanitizer ---------
  std::string lsm_dir = std::string(dir) + "_lsm";
  cmd = std::string("rm -rf ") + lsm_dir;
  if (system(cmd.c_str()) != 0) return 2;
  h = tkv_open2(lsm_dir.c_str(), 0, 1 << 16, 16 << 10 /*16KB budget*/,
                3 /*max runs -> frequent compaction*/, err, sizeof(err));
  if (!h) {
    fprintf(stderr, "lsm open failed: %s\n", err);
    return 1;
  }
  std::atomic<bool> lstop{false};
  std::vector<std::thread> lwriters;
  constexpr int kLsmPer = 1500;
  for (int w = 0; w < 2; ++w) {
    lwriters.emplace_back([&, w] {
      for (int i = 0; i < kLsmPer; ++i) {
        std::string k = "L" + std::to_string(w) + "-" + std::to_string(i);
        std::string ops = put_op(k, std::string(100, 'x'));
        char e[256];
        if (tkv_apply_batch(h, reinterpret_cast<const uint8_t*>(ops.data()),
                            static_cast<int64_t>(ops.size()), e,
                            sizeof(e)) != 0) {
          fprintf(stderr, "lsm put failed: %s\n", e);
          abort();
        }
      }
    });
  }
  std::thread lreader([&] {
    uint64_t i = 0;
    while (!lstop.load(std::memory_order_acquire)) {
      std::string k = "L0-" + std::to_string(i++ % kLsmPer);
      uint8_t* out = nullptr;
      int64_t n = tkv_get(h, 0, reinterpret_cast<const uint8_t*>(k.data()),
                          static_cast<int64_t>(k.size()), &out);
      if (n >= 0) tkv_free(out);
      uint8_t* sc = nullptr;
      n = tkv_scan(h, 0, nullptr, 0, nullptr, 0, 32, 1, i % 2, &sc);
      if (n >= 0) tkv_free(sc);
    }
  });
  for (auto& w : lwriters) w.join();
  lstop.store(true, std::memory_order_release);
  lreader.join();
  if (tkv_count(h, 0) != 2 * kLsmPer) {
    fprintf(stderr, "lsm count %lld != %d\n", (long long)tkv_count(h, 0),
            2 * kLsmPer);
    return 1;
  }
  int64_t runs = tkv_run_count(h);
  tkv_close(h);
  h = tkv_open2(lsm_dir.c_str(), 0, 1 << 16, 16 << 10, 3, err, sizeof(err));
  if (!h || tkv_count(h, 0) != 2 * kLsmPer) {
    fprintf(stderr, "lsm reopen count mismatch\n");
    return 1;
  }
  tkv_close(h);
  printf("check_kvstore LSM OK (%d entries, %lld runs, concurrent "
         "write/read/scan + background compaction)\n",
         2 * kLsmPer, (long long)runs);
  return 0;
}
